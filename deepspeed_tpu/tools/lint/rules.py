"""The dstpu-lint rule set: the stack's cross-layer contracts, as code.

Each rule encodes an invariant this codebase has actually been burned by
(see CHANGES.md review-round fixes and ``docs/tutorials/
static-analysis.md`` for the war stories):

- DSTPU001  eager ``jnp.*`` work at import time / in host scheduling code
- DSTPU002  host-sync calls inside the serving/verify/drafter hot paths
- DSTPU003  KV-cache writes or rewinds outside the ``models/common``
            ``append_kv_cache`` / ``set_cache_index`` contract
- DSTPU004  use of a buffer after it was donated to XLA
- DSTPU005  recompile hazards (inline jit, jit-in-loop, per-call string
            statics)
- DSTPU006  telemetry names referenced in docs/code must be declared in
            the registry

Analysis is intentionally repo-aware: hot paths, contract files and
device-call shapes are named below, because this linter's job is THIS
stack's contracts, not general python hygiene.  False positives are
expected to be rare and handled by ``# dstpu-lint: disable=RULE -- why``.
"""
from __future__ import annotations

import ast
import fnmatch
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import FileContext, Finding, Rule, register

# ---------------------------------------------------------------------------
# shared AST helpers
# ---------------------------------------------------------------------------


def dotted(node) -> str:
    """Best-effort dotted rendering of an expression: ``self._retire_fn``,
    ``jax.lax.dynamic_update_slice``, ``spec.verify_step()`` (calls keep
    ``()`` so patterns can anchor on them)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    if isinstance(node, ast.Call):
        base = dotted(node.func)
        return f"{base}()" if base else ""
    if isinstance(node, ast.Subscript):
        base = dotted(node.value)
        return f"{base}[]" if base else ""
    return ""


def _norm(display: str) -> str:
    return display.replace("\\", "/")


def _path_matches(display: str, globs: Sequence[str]) -> bool:
    p = _norm(display)
    return any(fnmatch.fnmatch(p, g) or p.endswith(g) for g in globs)


class _Aliases:
    """Per-file import aliases for numpy / jax.numpy / jax."""

    def __init__(self, tree: ast.Module):
        self.jnp: Set[str] = {"jax.numpy"}
        self.np: Set[str] = {"numpy"}
        self.jax: Set[str] = {"jax"}
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "jax.numpy":
                        self.jnp.add(a.asname or "jax.numpy")
                    elif a.name == "numpy":
                        self.np.add(a.asname or "numpy")
                    elif a.name == "jax":
                        self.jax.add(a.asname or "jax")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax":
                    for a in node.names:
                        if a.name == "numpy":
                            self.jnp.add(a.asname or "numpy")

    def is_jnp(self, d: str) -> bool:
        root = d.split(".")[0]
        return root in self.jnp or d.startswith("jax.numpy.")

    def is_np(self, d: str) -> bool:
        return d.split(".")[0] in self.np

    def is_jax(self, d: str) -> bool:
        return d.split(".")[0] in self.jax


_TRACED_DECORATORS = re.compile(
    r"(^|\.)(jit|vmap|pmap|grad|value_and_grad|checkpoint|remat|scan|"
    r"custom_vjp|custom_jvp|custom_vmap|compact|nowrap|kernel|"
    r"shard_map)\b")


class _Scopes(ast.NodeVisitor):
    """Classify every function def as traced (device) or host code.

    Traced: nested defs and lambdas (the repo's jitted functions are
    closures built inside host constructors), anything decorated with a
    jit/vmap/remat/compact-style transform, and the flax-traced methods
    (``__call__``/``setup``) of module classes.  Everything else —
    module-level defs and plain methods — is host code."""

    def __init__(self, tree: ast.Module):
        self.info: Dict[ast.AST, dict] = {}
        self._stack: List[ast.AST] = []
        self._class: List[ast.ClassDef] = []
        self.visit(tree)

    def _decorated_traced(self, node) -> bool:
        return any(_TRACED_DECORATORS.search(dotted(d) or "")
                   for d in node.decorator_list)

    def visit_ClassDef(self, node: ast.ClassDef):
        self._class.append(node)
        self.generic_visit(node)
        self._class.pop()

    def _visit_fn(self, node):
        in_function = any(isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))
                          for s in self._stack)
        is_method = (not in_function and self._class
                     and any(node in c.body for c in self._class[-1:]))
        traced = (in_function
                  or self._decorated_traced(node)
                  or (is_method and node.name in ("__call__", "setup")))
        qual = ".".join([c.name for c in self._class[-1:]]
                        + [node.name]) if is_method else node.name
        self.info[node] = {"traced": traced, "method": is_method,
                           "qualname": qual}
        self._stack.append(node)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_fn
    visit_AsyncFunctionDef = _visit_fn


def _functions(tree: ast.Module) -> List[ast.AST]:
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))]


def _enclosing_map(tree: ast.Module) -> Dict[ast.AST, Optional[ast.AST]]:
    """node -> innermost enclosing function def."""
    out: Dict[ast.AST, Optional[ast.AST]] = {}

    def walk(node, fn):
        out[node] = fn
        nxt = node if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)) else fn
        for child in ast.iter_child_nodes(node):
            walk(child, nxt)

    walk(tree, None)
    return out


def _own_statements(fn) -> List[ast.stmt]:
    """The function's statements in source order, NOT descending into
    nested function/lambda bodies (those trace later, on device)."""
    out: List[ast.stmt] = []

    def walk(stmts):
        for s in stmts:
            out.append(s)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for field in ("body", "orelse", "finalbody"):
                walk(getattr(s, field, []) or [])
            for h in getattr(s, "handlers", []) or []:
                walk(h.body)

    walk(fn.body)
    return out


def _expr_nodes(stmt: ast.stmt) -> Iterable[ast.AST]:
    """Expression nodes belonging to THIS statement: child statements are
    skipped (``_own_statements`` yields them separately — descending here
    would double-report) and so are nested def/lambda bodies (traced)."""
    def walk(node):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.stmt)):
                continue
            yield child
            yield from walk(child)

    yield from walk(stmt)


# ---------------------------------------------------------------------------
# DSTPU001 — eager jnp work at import time / in host scheduling code
# ---------------------------------------------------------------------------

# constructors that DISPATCH a device computation to build their result;
# zeros/asarray and friends are deliberate transfers and stay legal in
# host code (they are how operands reach the device at all)
_COMPUTE_CONSTRUCTORS = ("arange", "linspace", "logspace", "eye", "tri",
                        "indices", "meshgrid")

# modules whose top-level functions and methods are host-side scheduling
# code (everything else's top-level defs are traced library code called
# from inside jit)
_HOST_MODULES = ("*/inference/*.py", "*/runtime/engine.py", "*/launcher/*.py",
                 "*/autotuning/*.py", "*/monitor/*.py", "*/telemetry/*.py",
                 "*/elasticity/*.py", "*/utils/*.py", "*/profiling/*.py")


@register
class EagerJnpRule(Rule):
    id = "DSTPU001"
    name = "eager-jnp"
    doc = ("Eager jnp.* calls at module import time force early backend "
           "init; jnp.arange-style constructors in host scheduling code "
           "dispatch a device computation per call — build with np.* and "
           "transfer via jnp.asarray, or pass the values as arguments so "
           "offset variants reuse one executable (the PR-4 positions "
           "contract).")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        al = _Aliases(ctx.tree)
        findings: List[Finding] = []

        # (a) import-time scope: module body, class bodies, decorator
        # expressions and default arguments — all executed at import
        def import_time_exprs(body):
            for stmt in body:
                if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    for d in stmt.decorator_list:
                        yield d
                    for dflt in (stmt.args.defaults
                                 + [d for d in stmt.args.kw_defaults if d]):
                        yield dflt
                elif isinstance(stmt, ast.ClassDef):
                    for d in stmt.decorator_list:
                        yield d
                    yield from import_time_exprs(stmt.body)
                else:
                    yield stmt

        def eager_nodes(node):
            """Walk, PRUNING def/lambda subtrees (their bodies run later,
            on device) without abandoning sibling expressions — a lambda
            in a dict must not hide an eager call after it."""
            yield node
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                      ast.Lambda)):
                    continue
                yield from eager_nodes(child)

        for expr in import_time_exprs(ctx.tree.body):
            if isinstance(expr, ast.Lambda):
                continue        # e.g. a lambda default argument
            for node in eager_nodes(expr):
                if isinstance(node, ast.Call):
                    d = dotted(node.func)
                    if al.is_jnp(d) or (al.is_jax(d) and ".numpy." in d):
                        findings.append(ctx.finding(
                            self.id, node,
                            f"eager `{d}(...)` at import time initializes "
                            f"the jax backend on module import; build host "
                            f"constants with np.* (or defer into the "
                            f"function that needs them)"))

        # (b) host scheduling code: compute-producing constructors only
        host_wide = _path_matches(ctx.display, _HOST_MODULES)
        scopes = _Scopes(ctx.tree)
        for fn in _functions(ctx.tree):
            info = scopes.info.get(fn)
            if info is None or info["traced"]:
                continue
            if not (host_wide or info["method"]):
                continue   # top-level defs outside host modules: traced libs
            for stmt in _own_statements(fn):
                for node in _expr_nodes(stmt):
                    if not isinstance(node, ast.Call):
                        continue
                    d = dotted(node.func)
                    if not al.is_jnp(d):
                        continue
                    leaf = d.split(".")[-1]
                    if leaf in _COMPUTE_CONSTRUCTORS:
                        findings.append(ctx.finding(
                            self.id, node,
                            f"`{d}(...)` in host code `{info['qualname']}` "
                            f"dispatches a device computation per call; "
                            f"build with np.{leaf} and transfer via "
                            f"jnp.asarray (hoisting it if reused), or pass "
                            f"the values as a traced argument"))
        return findings


# ---------------------------------------------------------------------------
# DSTPU002 — host syncs inside the serving/verify/drafter hot paths
# ---------------------------------------------------------------------------

# (path glob, function qualname glob): the serving tick, the speculative
# verify tick, admission, and the drafters — one implicit sync here stalls
# every slot in the pool.  `# dstpu-lint: hotpath` on a def line opts
# additional functions in.
_HOTPATHS: Tuple[Tuple[str, str], ...] = (
    ("*/inference/serving.py", "ContinuousBatcher.step"),
    ("*/inference/serving.py", "ContinuousBatcher._spec_tick"),
    ("*/inference/serving.py", "ContinuousBatcher._admit"),
    ("*/inference/serving.py", "ContinuousBatcher._prefill*"),
    ("*/inference/serving.py", "ContinuousBatcher._shrink_parked"),
    ("*/inference/serving.py", "ContinuousBatcher._retire"),
    ("*/inference/specdec.py", "*.propose"),
    ("*/inference/specdec.py", "SpecDecoder.note_*"),
)

# callees whose results live on device: the repo's jitted-executable
# naming (slot/verify steps, admission fns, compiled prefill) plus raw
# jax/jnp calls handled separately
_DEVICE_CALL_RE = re.compile(
    r"(_fn\b|_fn\(|_step\b|_steps\[|_multi_step|compiled|verify_step|"
    r"\.apply\(|\.lower\(|_first_token_batch|_prefill\()")

_SYNC_SUFFIXES = (".item", ".block_until_ready")


@register
class HostSyncRule(Rule):
    id = "DSTPU002"
    name = "hotpath-sync"
    doc = ("Implicit host syncs (.item(), float()/int() on device arrays, "
           "np.asarray on device arrays, block_until_ready) inside the "
           "serving tick / verify / drafter hot paths serialize the "
           "pipeline; the ONE sanctioned sync is an explicit "
           "jax.device_get at the window boundary.")

    def _is_hot(self, ctx: FileContext, fn, qualname: str) -> bool:
        for pglob, qglob in _HOTPATHS:
            if _path_matches(ctx.display, (pglob,)) and \
                    fnmatch.fnmatch(qualname, qglob):
                return True
        first = fn.lineno
        deco_first = min([d.lineno for d in fn.decorator_list] or [first])
        return any(ln in ctx.hotpath_lines
                   for ln in range(deco_first - 1, fn.body[0].lineno))

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        al = _Aliases(ctx.tree)
        scopes = _Scopes(ctx.tree)
        findings: List[Finding] = []
        for fn in _functions(ctx.tree):
            info = scopes.info.get(fn)
            if info is None or info["traced"]:
                continue
            if not self._is_hot(ctx, fn, info["qualname"]):
                continue
            findings.extend(self._check_fn(ctx, al, fn, info["qualname"]))
        return findings

    # -- light intra-function taint: which names hold device arrays ----
    def _device_expr(self, e, al: _Aliases, taint: Set[str]) -> bool:
        if isinstance(e, ast.Call):
            d = dotted(e.func)
            if d.endswith("device_get") or al.is_np(d):
                return False          # the sanctioned sync / host data
            if al.is_jnp(d) or d.startswith(("jax.lax", "jax.random",
                                             "jax.nn", "jax.tree_util")):
                return True
            if _DEVICE_CALL_RE.search(d + "("):
                return True
            return any(self._device_expr(a, al, taint) for a in e.args)
        if isinstance(e, (ast.Name, ast.Attribute)):
            return dotted(e) in taint
        if isinstance(e, ast.Subscript):
            return self._device_expr(e.value, al, taint)
        return any(self._device_expr(c, al, taint)
                   for c in ast.iter_child_nodes(e)
                   if isinstance(c, ast.expr))

    @staticmethod
    def _targets(stmt) -> List[str]:
        tgts: List[ast.expr] = []
        if isinstance(stmt, ast.Assign):
            tgts = stmt.targets
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            tgts = [stmt.target]
        out: List[str] = []
        for t in tgts:
            if isinstance(t, (ast.Tuple, ast.List)):
                out.extend(dotted(e) for e in t.elts)
            else:
                out.append(dotted(t))
        return [t for t in out if t]

    @staticmethod
    def _is_metadata(arg) -> bool:
        """len(x), x.shape[...], x.ndim, x.dtype — shape/meta reads, not
        syncs."""
        if isinstance(arg, ast.Call) and dotted(arg.func) == "len":
            return True
        d = dotted(arg)
        return bool(re.search(r"\.(shape(\[\])?|ndim|dtype|size)$", d))

    def _check_fn(self, ctx, al, fn, qual) -> Iterable[Finding]:
        taint: Set[str] = set()
        findings: List[Finding] = []
        for stmt in _own_statements(fn):
            for node in _expr_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d.endswith(_SYNC_SUFFIXES):
                    recv = dotted(node.func.value) if isinstance(
                        node.func, ast.Attribute) else ""
                    if d.endswith(".block_until_ready") or recv in taint:
                        findings.append(ctx.finding(
                            self.id, node,
                            f"`{d}()` in hot path `{qual}` blocks the host "
                            f"per call; batch results and fetch once with "
                            f"jax.device_get at the window boundary"))
                elif d == "block_until_ready":
                    # the bare from-import form; dotted forms hit the
                    # _SYNC_SUFFIXES branch above
                    findings.append(ctx.finding(
                        self.id, node,
                        f"block_until_ready in hot path `{qual}` blocks "
                        f"the host per call; batch results and fetch once "
                        f"with jax.device_get at the window boundary"))
                elif d in ("float", "int", "bool") and node.args:
                    a = node.args[0]
                    if not self._is_metadata(a) and \
                            self._device_expr(a, al, taint):
                        findings.append(ctx.finding(
                            self.id, node,
                            f"`{d}()` on a device value in hot path "
                            f"`{qual}` is an implicit sync; jax.device_get "
                            f"the batch once instead"))
                elif al.is_np(d) and d.split(".")[-1] in ("asarray",
                                                          "array") \
                        and node.args:
                    if self._device_expr(node.args[0], al, taint):
                        findings.append(ctx.finding(
                            self.id, node,
                            f"`{d}(...)` on a device value in hot path "
                            f"`{qual}` syncs implicitly; wrap the fetch in "
                            f"jax.device_get explicitly (one batched get "
                            f"per window)"))
            # taint update AFTER checks: this statement's targets
            tgts = self._targets(stmt)
            if tgts:
                rhs = stmt.value if isinstance(
                    stmt, (ast.Assign, ast.AnnAssign)) else getattr(
                        stmt, "value", None)
                is_dev = rhs is not None and self._device_expr(rhs, al, taint)
                for t in tgts:
                    (taint.add if is_dev else taint.discard)(t)
        return findings


# ---------------------------------------------------------------------------
# DSTPU003 — KV-cache writes outside the models/common contract
# ---------------------------------------------------------------------------

_CACHE_CONTRACT_FILE = ("*/models/common.py",)
_CONTRACT_TOKENS = re.compile(
    r"cache_leaf_kind|cached_key|cached_value|cache_index|KV_CACHE_LEAVES")
_UPDATE_CALLS = re.compile(
    r"dynamic_update_slice(_in_dim)?$|dynamic_update_index_in_dim$")


@register
class CacheContractRule(Rule):
    id = "DSTPU003"
    name = "kv-cache-contract"
    doc = ("All KV-cache writes go through models/common.append_kv_cache "
           "and all write-head rewinds through set_cache_index; ad-hoc "
           "cache-leaf declarations or dynamic_update_slice/.at[].set/"
           "full_like on cache leaves elsewhere will drift from the "
           "fused/unfused layout contract (and from the paged pool's "
           "derived geometry).")

    def _own_text(self, ctx: FileContext, fn,
                  enclosing: Dict[ast.AST, Optional[ast.AST]]) -> str:
        """Source text of ``fn`` minus nested function bodies, so a parent
        function is not blamed for its traced children's contract use."""
        seg = ast.get_source_segment(ctx.src, fn) or ""
        for other in _functions(ctx.tree):
            if other is not fn and enclosing.get(other) is fn:
                sub = ast.get_source_segment(ctx.src, other)
                if sub:
                    seg = seg.replace(sub, "")
        return seg

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        if _path_matches(ctx.display, _CACHE_CONTRACT_FILE):
            return ()
        findings: List[Finding] = []
        enclosing = _enclosing_map(ctx.tree)
        touches_contract: Dict[ast.AST, bool] = {}

        def fn_touches(fn) -> bool:
            if fn is None:
                return False
            if fn not in touches_contract:
                touches_contract[fn] = bool(
                    _CONTRACT_TOKENS.search(self._own_text(ctx, fn,
                                                           enclosing)))
            return touches_contract[fn]

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            # (a) ad-hoc cache collection declarations
            if d.endswith(".variable") and len(node.args) >= 2 and \
                    isinstance(node.args[0], ast.Constant) and \
                    node.args[0].value == "cache":
                leaf = node.args[1].value if isinstance(
                    node.args[1], ast.Constant) else "?"
                findings.append(ctx.finding(
                    self.id, node,
                    f"ad-hoc cache leaf declaration "
                    f"(variable('cache', {leaf!r})) outside models/common; "
                    f"use append_kv_cache so the layout cannot drift from "
                    f"the KV_CACHE_LEAVES contract"))
                continue
            # (b) update ops / index rewinds in functions that walk cache
            # trees structurally
            leaf_name = d.split(".")[-1]
            is_update = bool(_UPDATE_CALLS.search(leaf_name))
            is_at_update = leaf_name in ("set", "add") and ".at[]" in d
            is_index_rewind = leaf_name == "full_like"
            if not (is_update or is_at_update or is_index_rewind):
                continue
            fn = enclosing.get(node)
            if isinstance(fn, ast.Lambda):
                fn = enclosing.get(fn)
            if fn_touches(fn):
                what = ("cache write-head rewind" if is_index_rewind or
                        is_at_update else "cache-leaf write")
                findings.append(ctx.finding(
                    self.id, node,
                    f"{what} (`{d}`) in a function that walks the cache "
                    f"tree, outside models/common; route writes through "
                    f"append_kv_cache and rewinds through set_cache_index"))
        return findings


# ---------------------------------------------------------------------------
# DSTPU004 — use after donation
# ---------------------------------------------------------------------------


@register
class UseAfterDonationRule(Rule):
    id = "DSTPU004"
    name = "use-after-donation"
    doc = ("An argument passed at a donate_argnums position is dead the "
           "moment the call dispatches — XLA may alias its buffer for the "
           "output.  Reading the donated variable afterwards (without "
           "rebinding it, typically to the call's own result) returns "
           "garbage on hardware even when CPU tests pass.")

    def _donating_callables(self, ctx: FileContext) -> Dict[str, Tuple[int, ...]]:
        """Names bound (directly or through wrappers like recompile.watch)
        to a jax.jit(..., donate_argnums=...) result, with the donated
        positions."""
        out: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            rhs = node.value
            if rhs is None:
                continue
            donated: Optional[Tuple[int, ...]] = None
            for call in ast.walk(rhs):
                if isinstance(call, ast.Call) and \
                        dotted(call.func).endswith("jit"):
                    for kw in call.keywords:
                        if kw.arg == "donate_argnums":
                            vals = []
                            for c in ast.walk(kw.value):
                                if isinstance(c, ast.Constant) and \
                                        isinstance(c.value, int):
                                    vals.append(c.value)
                            donated = tuple(vals)
            if donated:
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    name = dotted(t)
                    if name:
                        out[name] = donated
        return out

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        donators = self._donating_callables(ctx)
        if not donators:
            return ()
        findings: List[Finding] = []
        for fn in _functions(ctx.tree):
            findings.extend(self._check_fn(ctx, fn, donators))
        return findings

    def _check_fn(self, ctx, fn, donators) -> Iterable[Finding]:
        findings: List[Finding] = []
        stmts = _own_statements(fn)
        # dotted name -> (donation line, callee) for currently-dead values
        dead: Dict[str, Tuple[int, str]] = {}
        for stmt in stmts:
            assigned = HostSyncRule._targets(stmt)
            # reads first: a read of a dead name in this statement fires
            # unless this statement merely rebinds it without reading
            for node in _expr_nodes(stmt):
                if isinstance(node, (ast.Name, ast.Attribute)) and \
                        isinstance(getattr(node, "ctx", None), ast.Load):
                    d = dotted(node)
                    if d in dead:
                        line, callee = dead[d]
                        findings.append(ctx.finding(
                            self.id, node,
                            f"`{d}` was donated to `{callee}` on line "
                            f"{line} and read afterwards; donated buffers "
                            f"may be aliased by XLA — rebind the name to "
                            f"the call's result (or copy before donating)"))
                        del dead[d]   # one report per donation
            for name in assigned:
                dead.pop(name, None)
            # then record this statement's donations
            for node in _expr_nodes(stmt):
                if not isinstance(node, ast.Call):
                    continue
                callee = dotted(node.func)
                positions = donators.get(callee)
                if positions is None:
                    continue
                for pos in positions:
                    if pos < len(node.args):
                        d = dotted(node.args[pos])
                        if d and d not in assigned:
                            dead[d] = (node.lineno, callee)
        return findings


# ---------------------------------------------------------------------------
# DSTPU005 — recompile hazards
# ---------------------------------------------------------------------------


@register
class RecompileHazardRule(Rule):
    id = "DSTPU005"
    name = "recompile-hazard"
    doc = ("Each jax.jit object owns its executable cache: constructing "
           "one inline (jax.jit(f)(x)) or inside a loop retraces every "
           "call; a per-call string (f-string/str()/format) passed to a "
           "jitted callable is a distinct static value per call — every "
           "distinct value compiles a new executable (the recompile "
           "watchdog fires at runtime; this catches it at review time).")

    _JIT_RE = re.compile(r"(^|\.)(jit|pmap)$")
    _MEMO_DECOS = re.compile(r"(lru_cache|cache\b)")

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        enclosing = _enclosing_map(ctx.tree)
        donators = UseAfterDonationRule()._donating_callables(ctx)
        jitted_names = set(donators)
        for node in ast.walk(ctx.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)) and \
                    node.value is not None:
                if any(isinstance(c, ast.Call)
                       and self._JIT_RE.search(dotted(c.func))
                       for c in ast.walk(node.value)):
                    for t in (node.targets if isinstance(node, ast.Assign)
                              else [node.target]):
                        d = dotted(t)
                        if d:
                            jitted_names.add(d)

        loops: List[ast.AST] = [n for n in ast.walk(ctx.tree)
                                if isinstance(n, (ast.For, ast.While))]
        in_loop: Set[ast.AST] = set()
        for loop in loops:
            for sub in ast.walk(loop):
                in_loop.add(sub)

        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            # (a) inline-invoked jit: jax.jit(f)(x)
            if isinstance(node.func, ast.Call) and \
                    self._JIT_RE.search(dotted(node.func.func)):
                findings.append(ctx.finding(
                    self.id, node,
                    "jax.jit constructed and invoked inline — the "
                    "executable cache is discarded after the call and "
                    "every call retraces; bind the jitted callable once "
                    "(init-time attribute or lru_cached factory)"))
                continue
            # (b) jit constructed inside a loop (unless the enclosing
            # factory is memoized, the repo's per-width executable idiom)
            if self._JIT_RE.search(d) and node in in_loop:
                fn = enclosing.get(node)
                memoized = fn is not None and any(
                    self._MEMO_DECOS.search(dotted(deco))
                    for deco in getattr(fn, "decorator_list", []))
                if not memoized:
                    findings.append(ctx.finding(
                        self.id, node,
                        "jax.jit constructed inside a loop: each iteration "
                        "builds a fresh executable cache; hoist the jit (or "
                        "memoize the factory per static key, pow2-bucketed)"))
                continue
            # (c) per-call strings into jitted callables.  Callee match is
            # deliberately narrower than DSTPU002's taint patterns, and
            # telemetry-labelling kwargs (name=/site=/label=) are host
            # metadata, not statics of the executable.
            if d in jitted_names or re.search(r"_compiled_|\.lower\($",
                                              d + "("):
                for arg in list(node.args) + [
                        kw.value for kw in node.keywords
                        if kw.arg not in ("name", "site", "label",
                                          "reason", "help")]:
                    if isinstance(arg, ast.JoinedStr) or (
                            isinstance(arg, ast.Call)
                            and dotted(arg.func) in ("str", "format")
                            or isinstance(arg, ast.Call)
                            and dotted(arg.func).endswith(".format")):
                        findings.append(ctx.finding(
                            self.id, arg,
                            f"per-call string built in the signature of "
                            f"jitted callable `{d}`: every distinct value "
                            f"is a new static — key executables on bounded "
                            f"(pow2-bucketed) values instead"))
        return findings


# ---------------------------------------------------------------------------
# DSTPU006 — telemetry-name consistency
# ---------------------------------------------------------------------------

_METRIC_DECLS = ("counter", "gauge", "histogram")
_UNIT_SUFFIXES = ("total", "seconds", "ms", "bytes", "ratio", "rate", "len",
                  "depth", "slots", "info", "arrays", "port", "unixtime")
_NAME_SHAPE = re.compile(r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)+$")
_BACKTICK = re.compile(r"`([a-z][a-z0-9_]+)`")


@register
class TelemetryNamesRule(Rule):
    id = "DSTPU006"
    name = "telemetry-names"
    doc = ("Every metric name referenced in docs/tutorials or in code "
           "(flight-recorder pulls, dashboards) must exist in a registry "
           "declaration (telemetry_registry.counter/gauge/histogram): a "
           "renamed metric otherwise leaves dashboards silently empty.  "
           "f-string declarations count as wildcard patterns.")

    def __init__(self):
        self.declared: Set[str] = set()
        self.patterns: List[re.Pattern] = []
        self.decl_prefixes: Set[str] = set()
        # (display, line, name, where) to validate once declarations are
        # fully collected
        self.refs: List[Tuple[str, int, str, str]] = []
        self._decl_sites: Set[Tuple[str, int]] = set()

    # -- collection ----------------------------------------------------
    def collect(self, ctx: FileContext) -> None:
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d.split(".")[-1] in _METRIC_DECLS and node.args:
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    self.declared.add(arg.value)
                    self._decl_sites.add((ctx.display, arg.lineno))
                elif isinstance(arg, ast.JoinedStr):
                    pat = ""
                    for part in arg.values:
                        if isinstance(part, ast.Constant):
                            pat += re.escape(str(part.value))
                        else:
                            pat += r"[a-z0-9_]+"
                    self.patterns.append(re.compile(pat + r"\Z"))
                    self._decl_sites.add((ctx.display, arg.lineno))
                # bare Name args are forwarding wrappers (registry.py's
                # module-level counter()/gauge()) — not declarations
        # code references: metric-shaped string literals anywhere else
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    _NAME_SHAPE.match(node.value) and \
                    (ctx.display, node.lineno) not in self._decl_sites:
                self.refs.append((ctx.display, node.lineno, node.value,
                                  "code"))

    def collect_doc(self, path: Path, display: str, text: str) -> None:
        for i, line in enumerate(text.splitlines(), 1):
            for m in _BACKTICK.finditer(line):
                name = m.group(1)
                if _NAME_SHAPE.match(name):
                    self.refs.append((display, i, name, "doc"))

    # -- validation ------------------------------------------------------
    def _is_metric_shaped(self, name: str) -> bool:
        """Only tokens that are unambiguously metric names are checked:
        first segment must match a declared family prefix AND the last
        segment must be a unit suffix — config keys like
        train_micro_batch_size_per_gpu stay out of scope."""
        parts = name.split("_")
        return parts[0] in self.decl_prefixes and \
            parts[-1] in _UNIT_SUFFIXES

    def finalize(self) -> Iterable[Finding]:
        self.decl_prefixes = {n.split("_")[0] for n in self.declared}
        findings: List[Finding] = []
        seen: Set[Tuple[str, int, str]] = set()
        for display, line, name, where in self.refs:
            if not self._is_metric_shaped(name):
                continue
            if name in self.declared:
                continue
            if any(p.match(name) for p in self.patterns):
                continue
            key = (display, line, name)
            if key in seen:
                continue
            seen.add(key)
            src = "docs" if where == "doc" else "code"
            findings.append(Finding(
                self.id, display, line, 0,
                f"metric `{name}` referenced in {src} has no registry "
                f"declaration (counter/gauge/histogram) — fix the name or "
                f"declare it; dashboards reading it would stay empty"))
        return findings
