"""dstpu-lint: JAX-aware static analysis for the stack's own contracts.

Usage::

    python -m deepspeed_tpu.tools.lint deepspeed_tpu/ [--format=json]

Programmatic::

    from deepspeed_tpu.tools.lint import run_lint
    result = run_lint(["deepspeed_tpu/"])
    assert not result.active

The rule set (DSTPU001-006) encodes the trace/donation/cache/telemetry
contracts documented in ``docs/tutorials/static-analysis.md``; the
framework (registry, suppressions, output) lives in
:mod:`deepspeed_tpu.tools.lint.core`.
"""
from .core import (Finding, LintResult, Rule, all_rules,  # noqa: F401
                   register, render_json, render_text, run_lint)
