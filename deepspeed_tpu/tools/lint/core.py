"""dstpu-lint core: rule registry, suppression handling, runner, output.

The stack's correctness rests on cross-layer contracts that no general
linter knows about — positions-as-arguments, the ``append_kv_cache`` /
``set_cache_index`` cache discipline, donation lifetimes, executable-cache
hygiene, telemetry naming (see ``docs/tutorials/static-analysis.md``).
This module is the machinery; the contracts live in
:mod:`deepspeed_tpu.tools.lint.rules`.

Pure stdlib (``ast`` + ``tokenize``): the analyzer must run in a bare CI
job without the jax runtime.

Suppression grammar (one comment per line, rules comma-separated, the
justification after ``--`` is REQUIRED — an unexplained suppression is
itself a finding):

- ``# dstpu-lint: disable=DSTPU003 -- why this site is the exception``
- ``# dstpu-lint: disable-next-line=DSTPU001 -- reason``
- ``# dstpu-lint: disable-file=DSTPU006 -- reason`` (anywhere in the file)
- ``# dstpu-lint: hotpath`` on a ``def`` line opts the function into the
  hot-path rules (DSTPU002) in addition to the built-in path list.
"""
from __future__ import annotations

import ast
import dataclasses
import json
import re
import tokenize
from io import StringIO
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

# the framework's own diagnostics (parse failures, reason-less
# suppressions) — reported under this id so they gate CI like any rule
META_RULE = "DSTPU000"

_SUPPRESS_RE = re.compile(
    r"#\s*dstpu-lint:\s*(disable(?:-next-line|-file)?)\s*=\s*"
    r"([A-Za-z0-9_,\s]+?)\s*(?:--\s*(.*))?$")
_HOTPATH_RE = re.compile(r"#\s*dstpu-lint:\s*hotpath\b")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # path as given on the command line (relative)
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""   # the suppression's justification, when suppressed

    def to_json(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tail = f"  [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule} " \
               f"{self.message}{tail}"


@dataclasses.dataclass
class _Suppression:
    rules: Tuple[str, ...]     # () means "all rules"
    reason: str
    line: int
    file_wide: bool = False

    def covers(self, rule: str) -> bool:
        return not self.rules or rule in self.rules


class FileContext:
    """One parsed python file: source, AST, comment-derived metadata."""

    def __init__(self, path: Path, display: str, src: str):
        self.path = path
        self.display = display
        self.src = src
        self.lines = src.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree = ast.parse(src)
        except SyntaxError as e:
            self.parse_error = e
        # line -> suppressions (stacked disable-next-line comments can
        # land several on one code line); file-wide ones separate
        self.suppressions: Dict[int, List[_Suppression]] = {}
        self.file_suppressions: List[_Suppression] = []
        self.hotpath_lines: set = set()
        self._scan_comments()

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(StringIO(self.src).readline)
            comments = [(t.start[0], t.string) for t in tokens
                        if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # fall back to a line scan so a half-broken file still honors
            # its suppressions (strings containing '#' may false-match,
            # which at worst over-suppresses a broken file)
            comments = [(i + 1, line[line.index("#"):])
                        for i, line in enumerate(self.lines) if "#" in line]
        for line_no, text in comments:
            if _HOTPATH_RE.search(text):
                self.hotpath_lines.add(line_no)
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            kind, rules_s, reason = m.group(1), m.group(2), m.group(3) or ""
            rules = tuple(r.strip().upper() for r in rules_s.split(",")
                          if r.strip())
            if any(r == "ALL" for r in rules):
                rules = ()
            sup = _Suppression(rules, reason.strip(), line_no,
                               file_wide=(kind == "disable-file"))
            if kind == "disable-file":
                self.file_suppressions.append(sup)
            elif kind == "disable-next-line":
                self.suppressions.setdefault(
                    self._next_code_line(line_no), []).append(sup)
            else:
                self.suppressions.setdefault(line_no, []).append(sup)

    def _next_code_line(self, line_no: int) -> int:
        """First non-blank, non-comment line after ``line_no`` — stacked
        disable-next-line comments all bind to the statement they
        precede, not to each other."""
        for i in range(line_no, len(self.lines)):     # lines[i] = line i+1
            s = self.lines[i].strip()
            if s and not s.startswith("#"):
                return i + 1
        return line_no + 1

    def suppression_for(self, rule: str, line: int) -> Optional[_Suppression]:
        for sup in self.suppressions.get(line, ()):
            if sup.covers(rule):
                return sup
        for fs in self.file_suppressions:
            if fs.covers(rule):
                return fs
        return None

    def finding(self, rule: str, node, message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        col = getattr(node, "col_offset", 0)
        return Finding(rule, self.display, line, col, message)


class Rule:
    """Base rule.  Subclasses set ``id``/``name``/``doc`` and implement
    ``check(ctx)`` (per python file).  Rules needing cross-file state
    (DSTPU006) additionally implement ``collect(ctx)`` /
    ``collect_doc(path, text)`` and ``finalize()``."""

    id: str = ""
    name: str = ""
    doc: str = ""

    def check(self, ctx: FileContext) -> Iterable[Finding]:
        return ()

    def collect(self, ctx: FileContext) -> None:
        pass

    def collect_doc(self, path: Path, display: str, text: str) -> None:
        pass

    def finalize(self) -> Iterable[Finding]:
        return ()


_REGISTRY: Dict[str, type] = {}


def register(cls: type) -> type:
    assert cls.id and cls.id not in _REGISTRY, cls
    _REGISTRY[cls.id] = cls
    return cls


def all_rules() -> Dict[str, type]:
    # import for side effect: the rule classes register on first use
    from . import rules as _rules  # noqa: F401

    return dict(_REGISTRY)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]
    files_checked: int
    docs_checked: int

    @property
    def active(self) -> List[Finding]:
        return [f for f in self.findings if not f.suppressed]

    @property
    def suppressed(self) -> List[Finding]:
        return [f for f in self.findings if f.suppressed]

    def to_json(self) -> dict:
        counts: Dict[str, int] = {}
        for f in self.active:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "ok": not self.active,
            "files_checked": self.files_checked,
            "docs_checked": self.docs_checked,
            "counts_by_rule": counts,
            "findings": [f.to_json() for f in self.active],
            "suppressed": [f.to_json() for f in self.suppressed],
        }


def _iter_py_files(paths: Sequence[str]) -> Iterable[Tuple[Path, str]]:
    seen = set()
    for raw in paths:
        p = Path(raw)
        if p.is_dir():
            files = sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            files = [p]
        else:
            files = []
        for f in files:
            if "__pycache__" in f.parts:
                continue
            r = f.resolve()
            if r in seen:
                continue
            seen.add(r)
            yield f, str(f)


def _find_docs(paths: Sequence[str], docs: Optional[str]) -> List[Path]:
    """Doc tree for DSTPU006: explicit ``--docs``, else ``docs/`` next to
    (or one level above) the first scanned path."""
    if docs is not None:
        d = Path(docs)
        return sorted(d.rglob("*.md")) if d.is_dir() else []
    for raw in paths:
        base = Path(raw).resolve()
        if base.is_file():
            base = base.parent
        for root in (base, base.parent):
            d = root / "docs"
            if d.is_dir():
                return sorted(d.rglob("*.md"))
    return []


def run_lint(paths: Sequence[str], *, select: Sequence[str] = (),
             ignore: Sequence[str] = (),
             docs: Optional[str] = None) -> LintResult:
    """Lint ``paths`` (files or trees) with every registered rule.

    ``select``/``ignore`` filter by rule id.  Suppression comments are
    applied here — a suppressed finding stays in the result (JSON keeps
    the audit trail) but does not affect the exit status.  A suppression
    matching a finding but carrying no ``--`` justification raises a
    DSTPU000 finding at the same line: the repo's contract is
    suppress-WITH-reason."""
    rule_classes = all_rules()
    enabled = {rid: cls() for rid, cls in rule_classes.items()
               if (not select or rid in select) and rid not in ignore}
    findings: List[Finding] = []
    contexts: List[FileContext] = []

    files = list(_iter_py_files(paths))
    for path, display in files:
        try:
            src = path.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(META_RULE, display, 0, 0,
                                    f"unreadable file: {e}"))
            continue
        ctx = FileContext(path, display, src)
        if ctx.parse_error is not None:
            findings.append(Finding(
                META_RULE, display, ctx.parse_error.lineno or 0, 0,
                f"syntax error: {ctx.parse_error.msg}"))
            continue
        contexts.append(ctx)
        for rule in enabled.values():
            rule.collect(ctx)

    doc_files = _find_docs(paths, docs)
    for doc in doc_files:
        try:
            text = doc.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError):
            continue
        for rule in enabled.values():
            rule.collect_doc(doc, str(doc), text)

    for ctx in contexts:
        for rule in enabled.values():
            findings.extend(rule.check(ctx))
    for rule in enabled.values():
        findings.extend(rule.finalize())

    # apply suppressions (cross-file rules anchor findings to real file
    # contexts too, so look the context up by display path)
    by_display = {ctx.display: ctx for ctx in contexts}
    out: List[Finding] = []
    flagged_reasonless: set = set()
    for f in findings:
        ctx = by_display.get(f.path)
        sup = ctx.suppression_for(f.rule, f.line) if ctx else None
        if sup is not None:
            f.suppressed = True
            f.reason = sup.reason
            if not sup.reason:
                key = (f.path, sup.line, sup.file_wide)
                if key not in flagged_reasonless:
                    flagged_reasonless.add(key)
                    out.append(Finding(
                        META_RULE, f.path, sup.line, 0,
                        "suppression without a justification: append "
                        "'-- <one-line reason>'"))
        out.append(f)
    out.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return LintResult(out, files_checked=len(files),
                      docs_checked=len(doc_files))


def render_text(result: LintResult, show_suppressed: bool = False) -> str:
    lines = [f.render() for f in result.active]
    if show_suppressed:
        lines += [f.render() for f in result.suppressed]
    n_act, n_sup = len(result.active), len(result.suppressed)
    lines.append(
        f"dstpu-lint: {result.files_checked} files, {result.docs_checked} "
        f"docs; {n_act} finding{'s' if n_act != 1 else ''}"
        f" ({n_sup} suppressed)")
    return "\n".join(lines)


def render_json(result: LintResult) -> str:
    return json.dumps(result.to_json(), indent=2, sort_keys=True)
