"""Host-side step tracing in Chrome trace format (Perfetto-viewable).

``with trace.span("fwd-bwd"):`` records a complete ("X") event with
microsecond timestamps; the resulting JSON loads in ``ui.perfetto.dev``
or ``chrome://tracing`` and nests spans by containment, giving a
per-step timeline of the HOST side of training/serving — load-batch,
dispatch, device fetch, admission, prefill, decode ticks — the half of
the story ``jax.profiler`` device traces don't show.

Off by default and near-free when off: ``span.__enter__`` is one
attribute read.  Enable programmatically (:func:`enable`) or by setting
``DSTPU_TRACE=/path/to/trace.json`` — the file is written on interpreter
exit (and on :func:`save`).

Two bridges to device-side profiling:
- ``DSTPU_TRACE_JAX=1`` additionally wraps every span in a
  ``jax.profiler.TraceAnnotation``, so spans appear on the host track of
  a ``jax.profiler.trace()`` capture alongside device ops.
- :func:`device_span` returns a ``jax.named_scope`` usable INSIDE traced
  code (pipeline stage bodies): names land in HLO metadata and XLA
  profiles, where host spans cannot reach.
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Optional

__all__ = ["span", "device_span", "enable", "disable", "enabled", "clear",
           "save", "to_json", "add_span_observer", "remove_span_observer",
           "perf_to_trace_us", "TRACE_ENV", "TRACE_JAX_ENV"]

TRACE_ENV = "DSTPU_TRACE"
TRACE_JAX_ENV = "DSTPU_TRACE_JAX"

_MAX_EVENTS = 500_000    # hard cap: a forgotten enable() must not OOM the host


class _Tracer:
    def __init__(self):
        self.enabled = False
        self.jax_bridge = False
        self.events: list = []
        self.dropped = 0
        self.lock = threading.Lock()
        self.pid = os.getpid()
        # perf_counter has no defined epoch; one process-wide origin keeps
        # every thread's timestamps on a shared, roughly-unix-μs axis
        self.t0_ns = time.perf_counter_ns()

    def now_us(self) -> float:
        return (time.perf_counter_ns() - self.t0_ns) / 1e3


_tracer = _Tracer()

# Span observers: objects with ``span_enter(name)`` / ``span_exit(name,
# dur_s, args)`` notified on every span REGARDLESS of whether the Chrome-
# trace recorder is enabled — the goodput phase tracker and the crash
# flight recorder ride the same span boundaries the trace file does, but
# must work in production where tracing stays off.  An observer raising
# never breaks the instrumented code path.
_observers: list = []


def add_span_observer(obs) -> None:
    if obs not in _observers:
        _observers.append(obs)


def remove_span_observer(obs) -> None:
    if obs in _observers:
        _observers.remove(obs)


class span:
    """Context manager / decorator recording one complete trace event.

    ``args`` (small JSON-ables only) land in the event's ``args`` dict —
    visible in the Perfetto detail pane."""

    __slots__ = ("name", "args", "_t0", "_jax_ctx", "_rec")

    def __init__(self, name: str, **args):
        self.name = name
        self.args = args or None
        self._t0 = None
        self._jax_ctx = None
        self._rec = False

    def __enter__(self):
        if not _tracer.enabled and not _observers:
            return self
        self._rec = _tracer.enabled
        if self._rec and _tracer.jax_bridge:
            try:
                import jax.profiler

                self._jax_ctx = jax.profiler.TraceAnnotation(self.name)
                self._jax_ctx.__enter__()
            except Exception:
                self._jax_ctx = None
        for obs in _observers:
            try:
                obs.span_enter(self.name)
            except Exception:
                pass
        self._t0 = _tracer.now_us()
        return self

    def __exit__(self, exc_type, exc, tb):
        if self._t0 is None:
            return False
        t1 = _tracer.now_us()
        if self._jax_ctx is not None:
            try:
                self._jax_ctx.__exit__(exc_type, exc, tb)
            except Exception:
                pass
            self._jax_ctx = None
        if self._rec:
            ev = {"name": self.name, "ph": "X", "ts": self._t0,
                  "dur": t1 - self._t0, "pid": _tracer.pid,
                  "tid": threading.get_ident()}
            if self.args:
                ev["args"] = self.args
            with _tracer.lock:
                if len(_tracer.events) < _MAX_EVENTS:
                    _tracer.events.append(ev)
                else:
                    _tracer.dropped += 1
        for obs in _observers:
            try:
                obs.span_exit(self.name, (t1 - self._t0) / 1e6, self.args)
            except Exception:
                pass
        self._t0 = None
        self._rec = False
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*a, **kw):
            with span(self.name, **(self.args or {})):
                return fn(*a, **kw)

        return wrapped


def perf_to_trace_us(t_s: float) -> float:
    """Map a ``time.perf_counter()`` timestamp (seconds) onto this
    tracer's Chrome-trace microsecond axis.  The request tracer
    (``telemetry/reqtrace.py``) collects lifecycle timestamps from
    ``perf_counter`` and renders them through this helper, so retained
    request traces and the process span file share ONE Perfetto
    timeline."""
    return (t_s * 1e9 - _tracer.t0_ns) / 1e3


def device_span(name: str):
    """``jax.named_scope`` for use INSIDE jitted/traced code (host spans
    measure nothing there — tracing runs once).  The name lands in HLO op
    metadata, so XLA profiles and compiler dumps attribute work to it.
    Falls back to a no-op when jax is unavailable."""
    try:
        import jax

        return jax.named_scope(name)
    except Exception:
        import contextlib

        return contextlib.nullcontext()


def enable(jax_bridge: Optional[bool] = None) -> None:
    """Start recording spans.  ``jax_bridge=True`` mirrors every span
    into ``jax.profiler.TraceAnnotation`` (defaults to the
    ``DSTPU_TRACE_JAX`` env var)."""
    if jax_bridge is None:
        jax_bridge = os.environ.get(TRACE_JAX_ENV, "") not in ("", "0")
    _tracer.jax_bridge = bool(jax_bridge)
    _tracer.enabled = True


def disable() -> None:
    _tracer.enabled = False


def enabled() -> bool:
    return _tracer.enabled


def clear() -> None:
    with _tracer.lock:
        _tracer.events.clear()
        _tracer.dropped = 0


def to_json() -> dict:
    """Chrome-trace JSON object (the ``traceEvents`` wrapper form)."""
    with _tracer.lock:
        events = list(_tracer.events)
        dropped = _tracer.dropped
    meta = {"displayTimeUnit": "ms", "traceEvents": events}
    if dropped:
        meta["dstpu_dropped_events"] = dropped
    return meta


def save(path: str) -> str:
    """Write the trace JSON to ``path`` (atomic rename); returns the
    path.  Loadable with ``json.load`` and in Perfetto as-is."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(to_json(), fh)
    os.replace(tmp, path)
    return path


def _maybe_autostart() -> None:
    path = os.environ.get(TRACE_ENV)
    if not path:
        return
    enable()

    def _dump():
        try:
            p = path
            if "{rank}" in p:
                # multi-rank launches: one trace file per worker
                p = p.format(rank=os.environ.get("DSTPU_PROCESS_ID", "0"))
            save(p)
        except Exception:
            pass

    atexit.register(_dump)


_maybe_autostart()
