"""Recompilation watchdog: catch silent XLA recompiles in hot loops.

The single most expensive silent failure mode on TPU: a shape or dtype
that drifts between steps (a ragged final batch, a python float that
became an array, a cache that grew) makes ``jax.jit`` trace + compile a
NEW executable — seconds to minutes of stall that looks like "training
got slow" with no error anywhere.  The reference has nothing comparable
(CUDA eager mode doesn't recompile); on XLA it is the first thing to
rule out.

:func:`watch` wraps a jitted callable.  Each call computes a cheap
host-side signature — the args pytree structure plus every leaf's
(shape, dtype) — the shape/dtype part of the key ``jax.jit``'s C++
cache dispatches on; the part it cannot see (shardings, layouts) is
covered by a post-call ``_cache_size()`` cross-check: executable-count
growth on an already-known signature is also flagged as a recompile.
The FIRST distinct signature per watched site is the expected warm-up
compile; every NEW signature after that means the hot loop recompiled:

- ``xla_recompiles_total{site=...}`` increments (once per new signature);
- a rate-limited warning names the site and the offending leaf shapes,
  diffed against the previously seen signature when possible;
- where the wrapped function exposes ``_cache_size()`` (jitted
  callables do), the executable count is cross-checked into the log.

Sites whose signatures legitimately vary (chunked prefill compiles one
executable per power-of-two chunk BY DESIGN) pass ``warn=False``: their
compile population lands in ``xla_compiled_signatures_total`` only, so
``xla_recompiles_total`` stays a clean page-the-oncall alert metric.

Disable globally with ``DSTPU_RECOMPILE_WATCHDOG=0`` (``watch`` then
returns the callable unwrapped).
"""
from __future__ import annotations

import os
import time
from typing import Any, Optional

from ..utils.logging import logger
from . import registry as _registry

__all__ = ["watch", "RecompileWatchdog", "total_recompiles", "WATCHDOG_ENV"]

WATCHDOG_ENV = "DSTPU_RECOMPILE_WATCHDOG"

_WARN_INTERVAL_S = 30.0


def _leaf_sig(leaf: Any):
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is not None and dtype is not None:
        return (tuple(shape), str(dtype),
                bool(getattr(leaf, "weak_type", False)))
    # python scalars trace as weak-typed values: the VALUE does not key
    # the jit cache, the python type does
    return type(leaf).__name__


def _tree_sig(tree):
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (treedef, tuple(_leaf_sig(l) for l in leaves))


def _leaf_sigs_of(sig):
    out = []
    for part in sig:
        if part is not None:
            out.extend(part[1])
    return out


def _describe(sig) -> str:
    shapes = [f"{s[0]}:{s[1]}" for s in _leaf_sigs_of(sig)
              if isinstance(s, tuple)]
    head = ", ".join(shapes[:8])
    if len(shapes) > 8:
        head += f", … +{len(shapes) - 8} more"
    return head


def _diff(old_sig, new_sig) -> Optional[str]:
    """First differing leaf between two signatures with the same tree
    structure — usually THE offending argument."""
    if old_sig is None:
        return None
    old_parts = [p[0] for p in old_sig if p is not None]
    new_parts = [p[0] for p in new_sig if p is not None]
    if old_parts != new_parts:
        return None
    for i, (a, b) in enumerate(zip(_leaf_sigs_of(old_sig),
                                   _leaf_sigs_of(new_sig))):
        if a != b:
            return f"leaf #{i}: {a} -> {b}"
    return None


class _Watched:
    """Transparent wrapper: forwards ``__call__`` through the signature
    check, everything else (``lower``, ``_cache_size`` …) to the wrapped
    callable."""

    __slots__ = ("_fn", "_name", "_warn", "_dog", "_sigs", "_last_sig",
                 "_arg0_obj", "_arg0_sig", "_max_cache_size", "_settled")

    def __init__(self, fn, name: str, warn: bool, dog: "RecompileWatchdog"):
        self._fn = fn
        self._name = name
        self._warn = warn
        self._dog = dog
        self._sigs = set()
        self._last_sig = None          # signature of the PREVIOUS call —
        self._arg0_obj = None          # the loop that was actually running
        self._arg0_sig = None
        self._max_cache_size = None
        self._settled = False          # saw >=1 call with NO cache growth

    def _signature_of(self, args, kwargs):
        # (head, rest) pair: the first positional arg signed separately
        # with an identity memo — serving passes the same params tree
        # every tick; skip re-flattening its hundreds of leaves
        if args and args[0] is self._arg0_obj:
            head = self._arg0_sig
        elif args:
            head = _tree_sig((args[0],))
            self._arg0_obj = args[0]   # strong ref: pins the python tree
            self._arg0_sig = head      # (donated buffers are already
        else:                          # deleted; only wrappers persist)
            head = None
        return (head, _tree_sig((args[1:], kwargs)))

    def __call__(self, *args, **kwargs):
        try:
            sig = self._signature_of(args, kwargs)
        except Exception:
            sig = None   # unhashable leaf etc.: never break the hot path
        is_new = sig is not None and sig not in self._sigs
        if is_new:
            first = not self._sigs
            self._sigs.add(sig)
            self._dog._on_new_signature(self, sig, self._last_sig, first)
        self._last_sig = sig
        if is_new:
            # a new signature means this call pays trace+compile before
            # dispatch returns — bill it to the goodput "recompile" phase
            # (warm-up included: compile time is not goodput either way)
            t0 = time.perf_counter()
            out = self._fn(*args, **kwargs)
            try:
                from . import goodput

                goodput.note_compile(time.perf_counter() - t0)
            except Exception:
                pass
        else:
            out = self._fn(*args, **kwargs)
        # cross-check: jax.jit's C++ cache also keys on SHARDINGS and
        # layouts, which the host-side signature cannot see — if the
        # executable count grew on an already-known signature, the loop
        # recompiled anyway (e.g. a resharded state after checkpoint load)
        try:
            cs = self._fn._cache_size()
        except Exception:
            cs = None
        if cs is not None:
            if self._max_cache_size is not None and cs > self._max_cache_size:
                # growth counts only once the site has SETTLED (seen a
                # call with no growth): the warm-up phase legitimately
                # compiles per-layout variants as eager-built buffers are
                # replaced by committed jit outputs
                if self._settled and not is_new and sig is not None:
                    self._dog._on_hidden_recompile(self, cs)
            elif self._max_cache_size is not None:
                self._settled = True
            if self._max_cache_size is None or cs > self._max_cache_size:
                self._max_cache_size = cs
        return out

    def __getattr__(self, attr):
        return getattr(self._fn, attr)

    @property
    def signatures_seen(self) -> int:
        return len(self._sigs)


class RecompileWatchdog:
    def __init__(self, registry: Optional[_registry.Registry] = None,
                 warn_interval_s: float = _WARN_INTERVAL_S):
        self._registry = registry or _registry.get_registry()
        self._warn_interval_s = warn_interval_s
        self._last_warn: dict = {}
        self._recompiles = self._registry.counter(
            "xla_recompiles_total",
            "post-warm-up distinct jit signatures per watched site",
            labelnames=("site",))
        self._compiles = self._registry.counter(
            "xla_compiled_signatures_total",
            "all distinct jit signatures per watched site (warm-up "
            "included)", labelnames=("site",))

    def enabled(self) -> bool:
        return os.environ.get(WATCHDOG_ENV, "1") != "0"

    def watch(self, fn, name: str, warn: bool = True):
        """Wrap ``fn``; returns ``fn`` unchanged when the watchdog is
        disabled.  ``warn=False`` counts signatures without warning
        (for sites whose shapes vary by design)."""
        if not self.enabled():
            return fn
        return _Watched(fn, name, warn, self)

    def _on_new_signature(self, watched: _Watched, sig, prev_call_sig,
                          first: bool):
        self._compiles.labels(site=watched._name).inc()
        if first or not watched._warn:
            # warn=False sites vary by design: their compile population
            # stays out of the alert counter, which must mean "a hot loop
            # recompiled unexpectedly" and nothing else
            return
        self._recompiles.labels(site=watched._name).inc()
        if not self._should_warn(watched._name):
            return
        # diff against the PREVIOUS CALL's signature — the loop that was
        # actually running — not the last novel one
        diff = _diff(prev_call_sig, sig)
        cache_size = ""
        try:
            cs = watched._fn._cache_size()
            cache_size = f"; jit cache held {cs} executable(s) before this call"
        except Exception:
            pass
        detail = diff if diff is not None else \
            f"arg shapes now [{_describe(sig)}]"
        logger.warning(
            f"XLA RECOMPILE in hot loop {watched._name!r}: signature "
            f"#{len(watched._sigs)} after warm-up ({detail}){cache_size}. "
            f"Each recompile stalls the loop for the full compile time — "
            f"check for drifting batch/cache shapes or dtype flips.")

    def _on_hidden_recompile(self, watched: _Watched, cache_size: int):
        """Executable count grew on an already-known arg signature: the
        jit cache keys on shardings/layouts too, so the loop recompiled
        for a reason the shape signature cannot show."""
        self._compiles.labels(site=watched._name).inc()
        if not watched._warn:
            # by-design-varying sites (per-width placement etc.) hit this
            # legitimately — e.g. an uncommitted initial buffer becoming a
            # committed jit output; keep them out of the alert counter
            return
        self._recompiles.labels(site=watched._name).inc()
        if not self._should_warn(watched._name):
            return
        logger.warning(
            f"XLA RECOMPILE in hot loop {watched._name!r}: executable "
            f"count grew to {cache_size} with UNCHANGED arg shapes/dtypes "
            f"— the jit cache also keys on shardings and layouts; check "
            f"for a resharded params/state tree (e.g. after checkpoint "
            f"load or a mesh change).")

    def _should_warn(self, site: str) -> bool:
        now = time.monotonic()
        if now - self._last_warn.get(site, -1e18) < self._warn_interval_s:
            return False
        self._last_warn[site] = now
        return True


_default_watchdog: Optional[RecompileWatchdog] = None


def _get_default() -> RecompileWatchdog:
    global _default_watchdog
    if _default_watchdog is None:
        _default_watchdog = RecompileWatchdog()
    return _default_watchdog


def watch(fn, name: str, warn: bool = True):
    """Module-level convenience over the default watchdog."""
    return _get_default().watch(fn, name, warn=warn)


def total_recompiles() -> float:
    """Sum of ``xla_recompiles_total`` across sites (0.0 when nothing
    recompiled or the watchdog never armed)."""
    return _get_default()._recompiles.total()
