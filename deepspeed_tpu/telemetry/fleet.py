"""Fleet telemetry plane: multi-replica scrape/merge + replica health.

Every observability surface so far sees exactly ONE process: per-rank
``/metrics`` / ``/healthz`` / ``/statusz`` / ``/alertz`` exporters
(``telemetry/exporter.py``) with no consumer that can see two of them at
once.  The multi-replica serving plane (ROADMAP item 2 — prefix-cache-
aware routing, SLO-aware admission) is steered by telemetry, so the
fleet-level view is its load-bearing prerequisite.  This module is that
view, mirroring how Prometheus federation separates the per-replica
scrape surface from the cluster rollup schedulers consume:

- :func:`parse_prometheus` — the inverse of
  ``registry.render_prometheus()``: text → a ``snapshot()``-shaped
  structure.  ``registry.render_prometheus_snapshot(parse_prometheus(t))
  == t`` byte-for-byte (both directions share one renderer).
- :func:`merge_metrics` — merge semantics per metric kind: counters
  SUM across replicas, gauges are kept per-replica with min/max/sum
  rollups (summing a utilization gauge is a lie), histograms merge
  bucket-wise — guarded by a mismatched-bucket-schema check (a family
  whose ``le`` layout differs across replicas is skipped and reported,
  never silently mis-merged; see ``registry.BUCKET_SCHEMAS``).
- :func:`federate_metrics` — every replica's samples re-labeled with
  ``replica=<name>`` into one render-ready structure (the aggregator's
  federated ``/metrics``).
- :class:`ReplicaHealth` — a per-replica hysteresis state machine
  (``healthy``/``degraded``/``stale``/``down``; the ``anomaly.py``
  fire_after/clear_after pattern).  Scrape failures and ``/healthz``
  staleness are the inputs; transitions set
  ``fleet_replica_state{replica,state}`` and entering/leaving ``down``
  rides the alert machinery (``anomaly.emit_event`` →
  ``alerts_total{rule="fleet_replica_down"}``, ``/alertz``,
  subscribers) — exactly once per outage, not once per scrape.
- :class:`FleetView` — discovery (static ``host:port`` list,
  ``DSTPU_FLEET_REPLICAS`` env, or the ``fleet.json`` discovery file
  the launcher writes), a background scrape loop over the four
  endpoints, the merged rollup, and the programmatic seam the item-2
  router/admission controller will consume: ``replicas()``,
  ``healthy()``, ``best_for_prefix()``, ``total_queue_depth()``.
- :class:`FleetServer` — serves ``/fleetz`` (per-replica table +
  fleet rollups) and the federated ``/metrics``.

Stdlib-only (urllib + the registry): the aggregator runs standalone
(``scripts/fleetz.py``) without touching jax.
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..utils.logging import logger
from . import registry as _registry

__all__ = [
    "parse_prometheus", "merge_metrics", "federate_metrics",
    "histogram_quantile", "family_histogram", "metric_total",
    "stitch_tracez",
    "ReplicaHealth", "HEALTH_STATES", "FleetView", "FleetServer",
    "resolve_targets", "read_discovery", "FLEET_REPLICAS_ENV",
    "DISCOVERY_FILENAME",
]

FLEET_REPLICAS_ENV = "DSTPU_FLEET_REPLICAS"
DISCOVERY_FILENAME = "fleet.json"

# ---------------------------------------------------------------------------
# Prometheus text parsing — the inverse of registry.render_prometheus()
# ---------------------------------------------------------------------------

_NAME_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)")
_UNESCAPE = {"\\": "\\", '"': '"', "n": "\n"}


def _parse_labels(s: str) -> Dict[str, str]:
    """``k="v",k2="v2"`` (the brace interior) → ordered dict, undoing
    ``registry._escape_label_value`` (``\\\\``, ``\\"``, ``\\n``)."""
    labels: Dict[str, str] = {}
    i, n = 0, len(s)
    while i < n:
        j = s.index("=", i)
        name = s[i:j].strip()
        if j + 1 >= n or s[j + 1] != '"':
            raise ValueError(f"malformed label in {s!r}")
        i = j + 2
        out: List[str] = []
        while s[i] != '"':
            if s[i] == "\\" and i + 1 < n:
                out.append(_UNESCAPE.get(s[i + 1], s[i + 1]))
                i += 2
            else:
                out.append(s[i])
                i += 1
        i += 1                                   # closing quote
        labels[name] = "".join(out)
        if i < n and s[i] == ",":
            i += 1
    return labels


def _split_sample(line: str) -> Tuple[str, Dict[str, str], str]:
    """One exposition sample line → (name, labels, value-string).  The
    label scan is quote-aware: values may contain ``}``/`` ``/``,``."""
    m = _NAME_RE.match(line)
    if not m:
        raise ValueError(f"malformed sample line: {line!r}")
    name = m.group(1)
    rest = line[m.end():]
    labels: Dict[str, str] = {}
    if rest.startswith("{"):
        i, depth_q = 1, False
        while i < len(rest):
            c = rest[i]
            if c == "\\" and depth_q:
                i += 2
                continue
            if c == '"':
                depth_q = not depth_q
            elif c == "}" and not depth_q:
                break
            i += 1
        if i >= len(rest):
            raise ValueError(f"unterminated labels: {line!r}")
        labels = _parse_labels(rest[1:i])
        rest = rest[i + 1:]
    return name, labels, rest.strip()


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return float("inf")
    if s == "-Inf":
        return float("-inf")
    return float(s)


def parse_prometheus(text: str) -> dict:
    """Prometheus text (v0.0.4, as ``registry.render_prometheus()``
    emits it) → a ``Registry.snapshot()``-shaped dict: ``{name:
    {"type", "help", "labelnames", "samples": [...]}}``.

    Round-trip contract:
    ``registry.render_prometheus_snapshot(parse_prometheus(t)) == t``
    byte-for-byte for any ``t`` the renderer produced — metric and
    sample order, label order, bucket order, escaping and number
    formatting all survive.  Histogram ``le`` keys are kept as their
    rendered STRINGS (``"0.5"``, ``"+Inf"``): they are dict keys on
    both sides, so no float round-trip can perturb them."""
    out: Dict[str, dict] = {}
    helps: Dict[str, str] = {}
    # histogram samples grouped by (family, base-labels-minus-le)
    hist_rows: Dict[Tuple[str, tuple], dict] = {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, _, help_ = line[len("# HELP "):].partition(" ")
            helps[name] = help_
            continue
        if line.startswith("# TYPE "):
            name, _, kind = line[len("# TYPE "):].partition(" ")
            out[name] = {"type": kind.strip(), "help": helps.get(name, ""),
                         "labelnames": [], "samples": []}
            continue
        if line.startswith("#"):
            continue
        name, labels, value_s = _split_sample(line)
        base = None
        if name in out and out[name]["type"] != "histogram":
            base = name
        else:
            for suffix in ("_bucket", "_sum", "_count"):
                if name.endswith(suffix):
                    cand = name[:-len(suffix)]
                    if cand in out and out[cand]["type"] == "histogram":
                        base, name = cand, name
                        break
            if base is None and name in out:
                base = name                     # histogram-typed bare name
        if base is None:
            # sample with no TYPE line — tolerate (foreign exposition),
            # default to untyped gauge-like entry
            out[name] = {"type": "gauge", "help": helps.get(name, ""),
                         "labelnames": [], "samples": []}
            base = name
        entry = out[base]
        if entry["type"] == "histogram":
            key_labels = {k: v for k, v in labels.items() if k != "le"}
            key = (base, tuple(key_labels.items()))
            row = hist_rows.get(key)
            if row is None:
                row = {"labels": key_labels, "buckets": {},
                       "sum": 0.0, "count": 0}
                hist_rows[key] = row
                entry["samples"].append(row)
                if not entry["labelnames"]:
                    entry["labelnames"] = list(key_labels)
            if name.endswith("_bucket"):
                row["buckets"][labels.get("le", "+Inf")] = \
                    int(float(value_s))
            elif name.endswith("_sum"):
                row["sum"] = _parse_value(value_s)
            elif name.endswith("_count"):
                row["count"] = int(float(value_s))
        else:
            entry["samples"].append(
                {"labels": labels, "value": _parse_value(value_s)})
            if not entry["labelnames"]:
                entry["labelnames"] = list(labels)
    return out


# ---------------------------------------------------------------------------
# merge semantics per metric kind
# ---------------------------------------------------------------------------

def metric_total(parsed: Optional[dict], name: str) -> Optional[float]:
    """Sum of one family's sample values (counter/gauge) in a parsed
    scrape; None when absent."""
    if not parsed or name not in parsed:
        return None
    entry = parsed[name]
    if entry["type"] == "histogram":
        return float(sum(s.get("count", 0) for s in entry["samples"]))
    return float(sum(s.get("value", 0.0) for s in entry["samples"]))


def merge_metrics(per_replica: Dict[str, dict]
                  ) -> Tuple[dict, List[dict]]:
    """Merge N parsed scrapes into one fleet rollup.

    Per kind: **counters** sum per labelset; **gauges** keep per-replica
    values with ``min``/``max``/``sum`` rollups per labelset (a mean or
    sum alone would hide the straggler the fleet view exists to show);
    **histograms** merge bucket-wise (cumulative ``le`` counts add
    exactly — the registry's fixed-bucket design is WHY).  A histogram
    family whose bucket schema differs across replicas (``le`` key
    tuples unequal) is dropped from the merge and reported in the
    returned ``issues`` list; same for a family registered under
    different types.  Returns ``(merged, issues)``."""
    merged: Dict[str, dict] = {}
    issues: List[dict] = []
    skipped: set = set()
    for rep, parsed in per_replica.items():
        if not parsed:
            continue
        for name, entry in parsed.items():
            if name in skipped:
                continue
            cur = merged.get(name)
            if cur is None:
                cur = merged[name] = {
                    "type": entry["type"], "help": entry["help"],
                    "labelnames": list(entry["labelnames"]),
                    "samples": {}}
            elif cur["type"] != entry["type"]:
                issues.append({"metric": name, "kind": "type_conflict",
                               "replica": rep,
                               "detail": f"{cur['type']} vs "
                                         f"{entry['type']}"})
                skipped.add(name)
                del merged[name]
                continue
            if entry["type"] == "histogram":
                conflict = False
                for s in entry["samples"]:
                    schema = tuple(s["buckets"])
                    key = tuple(s["labels"].items())
                    dst = cur["samples"].get(key)
                    if dst is None:
                        cur["samples"][key] = {
                            "labels": dict(s["labels"]),
                            "buckets": dict(s["buckets"]),
                            "sum": float(s["sum"]),
                            "count": int(s["count"])}
                    elif tuple(dst["buckets"]) != schema:
                        issues.append({
                            "metric": name, "kind": "bucket_schema",
                            "replica": rep,
                            "detail": f"{list(dst['buckets'])} vs "
                                      f"{list(schema)}"})
                        conflict = True
                        break
                    else:
                        for le, c in s["buckets"].items():
                            dst["buckets"][le] += c
                        dst["sum"] += float(s["sum"])
                        dst["count"] += int(s["count"])
                if conflict:
                    skipped.add(name)
                    del merged[name]
            elif entry["type"] == "counter":
                for s in entry["samples"]:
                    key = tuple(s["labels"].items())
                    dst = cur["samples"].setdefault(
                        key, {"labels": dict(s["labels"]), "value": 0.0})
                    dst["value"] += float(s["value"])
            else:                                # gauge / untyped
                for s in entry["samples"]:
                    key = tuple(s["labels"].items())
                    dst = cur["samples"].setdefault(
                        key, {"labels": dict(s["labels"]),
                              "by_replica": {}, "min": None, "max": None,
                              "sum": 0.0})
                    v = float(s["value"])
                    dst["by_replica"][rep] = v
                    dst["min"] = v if dst["min"] is None \
                        else min(dst["min"], v)
                    dst["max"] = v if dst["max"] is None \
                        else max(dst["max"], v)
                    dst["sum"] += v
    # samples dicts → lists (JSON-able, order-stable)
    for entry in merged.values():
        entry["samples"] = list(entry["samples"].values())
    return merged, issues


def federate_metrics(per_replica: Dict[str, dict]
                     ) -> Tuple[dict, List[dict]]:
    """Union of every replica's families with a ``replica=<name>`` label
    injected FIRST on each sample — render-ready for the aggregator's
    federated ``/metrics`` (``registry.render_prometheus_snapshot``).
    Type conflicts across replicas drop the later replica's family (and
    land in ``issues``); bucket schemas may legitimately differ here —
    each sample keeps its own buckets, label-disambiguated."""
    out: Dict[str, dict] = {}
    issues: List[dict] = []
    for rep, parsed in per_replica.items():
        if not parsed:
            continue
        for name, entry in parsed.items():
            cur = out.get(name)
            if cur is None:
                cur = out[name] = {
                    "type": entry["type"], "help": entry["help"],
                    "labelnames": ["replica"] + list(entry["labelnames"]),
                    "samples": []}
            elif cur["type"] != entry["type"]:
                issues.append({"metric": name, "kind": "type_conflict",
                               "replica": rep,
                               "detail": f"{cur['type']} vs "
                                         f"{entry['type']}"})
                continue
            for s in entry["samples"]:
                labels = {"replica": rep, **s["labels"]}
                fs = dict(s)
                fs["labels"] = labels
                cur["samples"].append(fs)
    return out, issues


def stitch_tracez(per_replica: Dict[str, Optional[dict]]) -> dict:
    """Merge replicas' ``/tracez?full=1`` payloads by trace id — the
    cross-replica request-trace view (``telemetry/reqtrace.py``).

    Once prefill/decode disaggregate and requests hop replicas, one
    request's spans land on several exporters under ONE propagated
    trace id (the ``traceparent`` contract).  This stitches them back
    together: every span is labeled ``replica=<name>``, spans within a
    trace sort by their UNIX-mapped start time (each replica's payload
    carries a ``clock_offset_s`` anchoring its monotonic span clock to
    wall time — replicas' ``perf_counter`` origins are unrelated, so
    raw ``t0_s`` values must never be compared across replicas), and
    the per-replica segments (uid, retention reason, slo_ok) are kept
    under ``segments``.  Tolerant of ``None``/index-only payloads (a
    replica with tracing off contributes nothing)."""
    traces: Dict[str, dict] = {}
    for rep, payload in per_replica.items():
        if not payload:
            continue
        for tr in payload.get("traces") or []:
            tid = tr.get("trace_id")
            if not tid:
                continue
            dst = traces.get(tid)
            if dst is None:
                dst = traces[tid] = {"trace_id": tid, "replicas": [],
                                     "segments": [], "spans": []}
            if rep not in dst["replicas"]:
                dst["replicas"].append(rep)
            off = float(tr.get("clock_offset_s") or 0.0)
            dst["segments"].append({
                "replica": rep, "uid": tr.get("uid"),
                "retained": tr.get("retained"), "slo_ok": tr.get("slo_ok"),
                "n_out": tr.get("n_out"), "ttft_ms": tr.get("ttft_ms"),
                "tpot_ms": tr.get("tpot_ms"), "t_unix": tr.get("t_unix")})
            for s in tr.get("spans") or []:
                span = dict(s)
                span["replica"] = rep
                span["t0_unix"] = s["t0_s"] + off
                span["t1_unix"] = s["t1_s"] + off
                dst["spans"].append(span)
    for dst in traces.values():
        dst["spans"].sort(key=lambda s: s["t0_unix"])
        dst["cross_replica"] = len(dst["replicas"]) > 1
    order = sorted(traces.values(),
                   key=lambda t: max((s.get("t_unix") or 0.0
                                      for s in t["segments"]), default=0.0),
                   reverse=True)
    return {"traces": order,
            "n_traces": len(order),
            "n_cross_replica": sum(1 for t in order if t["cross_replica"])}


def histogram_quantile(sample: dict, q: float) -> Optional[float]:
    """Nearest-rank quantile over a cumulative-bucket histogram sample,
    using THE ``registry.pct`` convention (index ``min(count-1,
    int(q*count))`` over the sorted observations): returns the upper
    bound (``le``) of the bucket holding that observation.  None on an
    empty histogram or when the rank lands in ``+Inf``."""
    count = int(sample.get("count", 0))
    if count <= 0:
        return None
    idx = min(count - 1, int(q * count))
    for le_s, cum in sample["buckets"].items():
        if cum > idx:
            if le_s == "+Inf":
                return None
            return float(le_s)
    return None


def family_histogram(entry: Optional[dict]) -> Optional[dict]:
    """Collapse a (merged) histogram family's labelsets into one
    cumulative-bucket sample — safe because the merge guard already
    enforced a single bucket schema per family."""
    if not entry or entry.get("type") != "histogram" \
            or not entry["samples"]:
        return None
    first = entry["samples"][0]
    acc = {"labels": {}, "buckets": dict(first["buckets"]),
           "sum": float(first["sum"]), "count": int(first["count"])}
    for s in entry["samples"][1:]:
        if tuple(s["buckets"]) != tuple(acc["buckets"]):
            return None
        for le, c in s["buckets"].items():
            acc["buckets"][le] += c
        acc["sum"] += float(s["sum"])
        acc["count"] += int(s["count"])
    return acc


# ---------------------------------------------------------------------------
# replica health state machine
# ---------------------------------------------------------------------------

HEALTH_STATES = ("healthy", "degraded", "stale", "down")


class ReplicaHealth:
    """Hysteresis state machine over one replica's scrape outcomes (the
    ``anomaly.py`` fire_after/clear_after pattern, per replica).

    Inputs per scrape round: did the ``/metrics`` fetch succeed, and did
    ``/healthz`` report ok (None = endpoint unavailable, treated as
    neutral).  States:

    - ``healthy`` — scrapes succeed, ``/healthz`` ok.
    - ``degraded`` — scrapes succeed but ``/healthz`` reports not-ok
      (heartbeat/step staleness: the worker process is alive but its
      loop is wedged) for ``degrade_after`` consecutive rounds.
    - ``stale`` — ``stale_after`` consecutive scrape failures: no fresh
      data, not yet presumed dead.  Also the INITIAL state (an
      undiscovered replica has no fresh data by definition).
    - ``down`` — ``down_after`` consecutive scrape failures.  Entering
      fires exactly ONE ``fleet_replica_down`` alert; leaving clears it.

    Flap suppression: recovery from ``stale``/``down`` needs
    ``clear_after`` consecutive successful scrapes (first contact after
    discovery needs just one — nothing to suppress yet), and any
    success resets the failure streak, so alternating fail/ok neither
    fires nor clears anything."""

    def __init__(self, stale_after: int = 2, down_after: int = 5,
                 degrade_after: int = 2, clear_after: int = 2):
        if not (0 < stale_after <= down_after):
            raise ValueError("need 0 < stale_after <= down_after")
        self.stale_after = stale_after
        self.down_after = down_after
        self.degrade_after = degrade_after
        self.clear_after = clear_after
        self.state = "stale"
        self._ever_ok = False
        self._fails = 0
        self._oks = 0
        self._bad_health = 0
        self._good_health = 0

    def observe(self, scrape_ok: bool,
                healthz_ok: Optional[bool] = None
                ) -> Optional[Tuple[str, str]]:
        """Fold one scrape round in; returns ``(old, new)`` on a state
        transition, None otherwise."""
        old = self.state
        if not scrape_ok:
            self._fails += 1
            self._oks = 0
            if self._fails >= self.down_after:
                self.state = "down"
            elif self._fails >= self.stale_after and old != "down":
                self.state = "stale"
        else:
            first_contact = not self._ever_ok
            self._fails = 0
            self._oks += 1
            self._ever_ok = True
            if healthz_ok is False:
                self._bad_health += 1
                self._good_health = 0
            else:
                self._good_health += 1
                self._bad_health = 0
            if old in ("stale", "down"):
                need = 1 if first_contact else self.clear_after
                if self._oks >= need:
                    self.state = "degraded" if self._bad_health > 0 \
                        else "healthy"
            elif old == "healthy":
                if self._bad_health >= self.degrade_after:
                    self.state = "degraded"
            elif old == "degraded":
                if self._good_health >= self.clear_after:
                    self.state = "healthy"
        return (old, self.state) if self.state != old else None


# ---------------------------------------------------------------------------
# discovery
# ---------------------------------------------------------------------------

def read_discovery(path: str) -> List[dict]:
    """Parse the launcher-written ``fleet.json``: ``{"replicas":
    [{"rank", "host", "port", ...}, ...]}`` → the replica entry list
    (sorted by rank).  Raises on unreadable/malformed files — the
    caller decides whether absence is an error (CLI) or a wait state
    (the watch loop)."""
    with open(path) as fh:
        doc = json.load(fh)
    reps = doc.get("replicas")
    if not isinstance(reps, list):
        raise ValueError(f"{path}: no 'replicas' list")
    out = []
    for r in reps:
        if "host" not in r or "port" not in r:
            raise ValueError(f"{path}: replica entry missing host/port: "
                             f"{r!r}")
        out.append(dict(r))
    out.sort(key=lambda r: (r.get("rank", 1 << 30), r["host"],
                            int(r["port"])))
    return out


def resolve_targets(targets: Optional[Sequence[str]] = None,
                    discovery_file: Optional[str] = None
                    ) -> Dict[str, str]:
    """Resolve ``{name: host:port}`` from (in precedence order) an
    explicit target list, a discovery file, or the
    ``DSTPU_FLEET_REPLICAS`` env (comma-separated ``host:port``).
    Static targets are named by their target string; discovered ones
    ``rank<k>``."""
    if targets:
        return {str(t): str(t) for t in targets}
    if discovery_file:
        entries = read_discovery(discovery_file)
        return {f"rank{r.get('rank', i)}": f"{r['host']}:{r['port']}"
                for i, r in enumerate(entries)}
    env = os.environ.get(FLEET_REPLICAS_ENV, "")
    if env.strip():
        return {t.strip(): t.strip() for t in env.split(",") if t.strip()}
    return {}


# ---------------------------------------------------------------------------
# the aggregator
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ReplicaInfo:
    """One row of the ``/fleetz`` per-replica table — the read-only
    snapshot ``FleetView.replicas()`` hands the router."""
    name: str
    target: str
    state: str
    scrapes: int
    failures: int
    last_scrape_age_s: Optional[float]
    queue_depth: Optional[float]
    active_slots: Optional[float]
    prefix_hit_rate: Optional[float]
    goodput_ratio: Optional[float]
    ttft_p99_ms: Optional[float]
    tpot_p99_ms: Optional[float]
    active_alerts: List[str]

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class _Rep:
    """Aggregator-internal per-replica state: scrape results + health."""

    def __init__(self, name: str, target: str, health: ReplicaHealth):
        self.name = name
        self.target = target
        self.health = health
        self.metrics: Optional[dict] = None      # last GOOD parse
        self.statusz: Optional[dict] = None
        self.healthz: Optional[dict] = None
        self.alertz: Optional[dict] = None
        self.scrapes = 0
        self.failures = 0
        self.last_ok_mono: Optional[float] = None
        self.last_error: Optional[str] = None

    def counter_total(self, name: str) -> float:
        v = metric_total(self.metrics, name)
        return 0.0 if v is None else v


class FleetView:
    """Scrapes N replica exporters and merges them into one fleet view.

    The programmatic surface (``replicas()`` / ``healthy()`` /
    ``best_for_prefix()`` / ``total_queue_depth()``) is the explicit
    seam the multi-replica router and admission controller consume —
    the fleet analog of ``anomaly.subscribe()``.

    Discovery: pass ``targets`` (static), ``discovery_file`` (the
    launcher-written ``fleet.json``, re-read when its mtime moves, so a
    restarted worker's new OS-assigned port is picked up mid-flight),
    or neither (``DSTPU_FLEET_REPLICAS`` env).  ``scrape_once()`` runs
    one synchronous round; ``start()`` runs rounds on a daemon thread.
    """

    def __init__(self, targets: Optional[Sequence[str]] = None, *,
                 discovery_file: Optional[str] = None,
                 interval_s: float = 2.0, timeout_s: float = 2.0,
                 registry: Optional[_registry.Registry] = None,
                 anomaly_engine=None,
                 health_knobs: Optional[dict] = None):
        self._static_targets = list(targets) if targets else None
        self.discovery_file = discovery_file
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.registry = registry or _registry.get_registry()
        self._anomaly = anomaly_engine
        self._health_knobs = dict(health_knobs or {})
        self._lock = threading.RLock()
        self._reps: Dict[str, _Rep] = {}
        self._discovery_mtime: Optional[float] = None
        self._rounds = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = self.registry
        self._m_replicas = reg.gauge(
            "fleet_replicas", "replicas known to the fleet aggregator")
        self._m_state = reg.gauge(
            "fleet_replica_state",
            "1 for the replica's current health state "
            "(healthy/degraded/stale/down), 0 otherwise",
            labelnames=("replica", "state"))
        self._m_scrapes = reg.counter(
            "fleet_scrapes_total", "successful replica /metrics scrapes",
            labelnames=("replica",))
        self._m_failures = reg.counter(
            "fleet_scrape_failures_total", "failed replica scrapes",
            labelnames=("replica",))
        self._m_schema_conflicts = reg.counter(
            "fleet_bucket_schema_conflicts_total",
            "histogram families dropped from a merge evaluation because "
            "bucket schemas differed across replicas (counted per "
            "evaluation: a nonzero rate = ongoing schema skew)")
        self._m_scrape_ms = reg.histogram(
            "fleet_scrape_ms", "per-replica scrape round-trip",
            labelnames=("replica",), buckets=_registry.MS_BUCKETS)
        self._m_queue = reg.gauge(
            "fleet_total_queue_depth",
            "summed queue depth across non-down replicas")
        self._refresh_targets(force=True)

    # -- discovery ------------------------------------------------------
    def _refresh_targets(self, force: bool = False) -> None:
        if self._static_targets is not None:
            mapping = {t: t for t in self._static_targets}
        elif self.discovery_file:
            try:
                mtime = os.path.getmtime(self.discovery_file)
            except OSError:
                return                       # not written yet: keep known
            if not force and mtime == self._discovery_mtime:
                return
            try:
                entries = read_discovery(self.discovery_file)
            except Exception as e:
                logger.warning(f"fleet: unreadable discovery file "
                               f"{self.discovery_file}: {e!r}")
                return
            self._discovery_mtime = mtime
            mapping = {f"rank{r.get('rank', i)}": f"{r['host']}:{r['port']}"
                       for i, r in enumerate(entries)}
        else:
            mapping = resolve_targets()
        with self._lock:
            for name, target in mapping.items():
                rep = self._reps.get(name)
                if rep is None:
                    self._reps[name] = _Rep(
                        name, target, ReplicaHealth(**self._health_knobs))
                    self._set_state_gauge(name, "stale")
                elif rep.target != target:
                    # a restarted worker came back on a new port: fresh
                    # scrape history, fresh health machine
                    logger.info(f"fleet: replica {name} moved "
                                f"{rep.target} -> {target}")
                    self._clear_down_alert(rep)
                    self._reps[name] = _Rep(
                        name, target, ReplicaHealth(**self._health_knobs))
                    self._set_state_gauge(name, "stale")
            for name in [n for n in self._reps if n not in mapping]:
                self._clear_down_alert(self._reps[name])
                # zero the state series: the registry has no labelset
                # removal, and a 1.0 left behind would report the
                # removed replica's last state forever
                for s in HEALTH_STATES:
                    self._m_state.labels(replica=name, state=s).set(0.0)
                del self._reps[name]
            self._m_replicas.set(float(len(self._reps)))

    # -- scraping -------------------------------------------------------
    def _fetch(self, target: str, path: str) -> Tuple[int, bytes]:
        """GET ``http://target{path}``; returns (status, body).  An HTTP
        error status (the /healthz 503) is a RESPONSE, not a failure —
        only transport errors raise.  Override/monkeypatch point for
        socket-free tests."""
        try:
            with urllib.request.urlopen(f"http://{target}{path}",
                                        timeout=self.timeout_s) as r:
                return r.status, r.read()
        except urllib.error.HTTPError as e:
            return e.code, e.read()

    def _scrape_replica(self, rep: _Rep) -> bool:
        t0 = time.monotonic()
        ok = False
        healthz_ok: Optional[bool] = None
        try:
            code, body = self._fetch(rep.target, "/metrics")
            if code == 200:
                rep.metrics = parse_prometheus(body.decode())
                ok = True
                rep.last_error = None
            else:
                rep.last_error = f"/metrics HTTP {code}"
        except Exception as e:
            rep.last_error = repr(e)
        if ok:
            rep.last_ok_mono = time.monotonic()
            rep.scrapes += 1
            self._m_scrapes.labels(replica=rep.name).inc()
            self._m_scrape_ms.labels(replica=rep.name).observe(
                (time.monotonic() - t0) * 1e3)
            for path, attr in (("/statusz", "statusz"),
                               ("/healthz", "healthz"),
                               ("/alertz", "alertz")):
                try:
                    _, body = self._fetch(rep.target, path)
                    setattr(rep, attr, json.loads(body.decode()))
                except Exception:
                    setattr(rep, attr, None)
            if rep.healthz is not None:
                healthz_ok = bool(rep.healthz.get("ok", True))
        else:
            rep.failures += 1
            self._m_failures.labels(replica=rep.name).inc()
        transition = rep.health.observe(ok, healthz_ok)
        if transition is not None:
            self._on_transition(rep, *transition)
        return ok

    def _set_state_gauge(self, name: str, state: str) -> None:
        for s in HEALTH_STATES:
            self._m_state.labels(replica=name, state=s).set(
                1.0 if s == state else 0.0)

    def _alert_engine(self):
        if self._anomaly is not None:
            return self._anomaly
        from . import anomaly as _anomaly

        return _anomaly.get_engine()

    def _on_transition(self, rep: _Rep, old: str, new: str) -> None:
        # degradations warn (and so ride the flight-recorder log ring);
        # recoveries and first contact just inform
        log = logger.warning if HEALTH_STATES.index(new) > \
            HEALTH_STATES.index(old) else logger.info
        log(f"fleet: replica {rep.name} ({rep.target}) {old} -> {new}")
        self._set_state_gauge(rep.name, new)
        try:
            if new == "down":
                self._alert_engine().emit_event(
                    "fleet_replica_down", "firing",
                    key=f"fleet_replica_down[{rep.name}]",
                    detail={"replica": rep.name, "target": rep.target,
                            "from": old, "last_error": rep.last_error})
            elif old == "down":
                self._alert_engine().emit_event(
                    "fleet_replica_down", "cleared",
                    key=f"fleet_replica_down[{rep.name}]",
                    detail={"replica": rep.name, "target": rep.target,
                            "to": new})
        except Exception as e:      # alerting must never break scraping
            logger.warning(f"fleet: alert dispatch failed: {e!r}")

    def _clear_down_alert(self, rep: _Rep) -> None:
        if rep.health.state == "down":
            try:
                self._alert_engine().emit_event(
                    "fleet_replica_down", "cleared",
                    key=f"fleet_replica_down[{rep.name}]",
                    detail={"replica": rep.name, "target": rep.target,
                            "to": "removed"})
            except Exception:
                pass

    def scrape_once(self) -> dict:
        """One scrape round over every known replica; returns
        ``{name: scrape_ok}``.  Replicas are scraped CONCURRENTLY (a
        small thread pool): one blackholed host costing a full
        ``timeout_s`` must not age every other replica's data past the
        scrape interval — per-replica state is owned by its scrape, and
        the registry/alert sinks are thread-safe."""
        self._refresh_targets()
        with self._lock:
            reps = list(self._reps.values())
        if len(reps) <= 1:
            results = {rep.name: self._scrape_replica(rep)
                       for rep in reps}
        else:
            from concurrent.futures import ThreadPoolExecutor

            with ThreadPoolExecutor(
                    max_workers=min(8, len(reps)),
                    thread_name_prefix="dstpu-fleet") as pool:
                futs = {rep.name: pool.submit(self._scrape_replica, rep)
                        for rep in reps}
                results = {name: f.result() for name, f in futs.items()}
        with self._lock:
            self._rounds += 1
            self._m_queue.set(self._total_queue_locked())
        return results

    def start(self) -> "FleetView":
        """Run scrape rounds on a daemon thread every ``interval_s``."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def loop():
            while not self._stop.wait(self.interval_s):
                try:
                    self.scrape_once()
                except Exception as e:   # the loop must survive anything
                    logger.warning(f"fleet: scrape round failed: {e!r}")
        self._thread = threading.Thread(
            target=loop, name="dstpu-fleet-scrape", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.timeout_s + self.interval_s)
            self._thread = None

    # -- the consumer seam (router / admission controller) -------------
    def _replica_info(self, rep: _Rep) -> ReplicaInfo:
        serving = (rep.statusz or {}).get("serving") or {}
        hit = metric_total(rep.metrics, "prefix_cache_hit_tokens_total")
        miss = metric_total(rep.metrics, "prefix_cache_miss_tokens_total")
        hit_rate = None
        if hit is not None and miss is not None and hit + miss > 0:
            hit_rate = hit / (hit + miss)
        ttft = serving.get("ttft_p99_ms")
        if ttft is None and rep.metrics is not None:
            h = family_histogram(rep.metrics.get("serving_ttft_seconds"))
            if h is not None:
                q = histogram_quantile(h, 0.99)
                ttft = None if q is None else q * 1e3
        tpot = serving.get("tpot_p99_ms")
        if tpot is None and rep.metrics is not None:
            h = family_histogram(rep.metrics.get("serving_tpot_ms"))
            if h is not None:
                tpot = histogram_quantile(h, 0.99)
        alerts = sorted({a.get("rule", "?")
                         for a in (rep.alertz or {}).get("active", [])})
        age = None if rep.last_ok_mono is None \
            else round(time.monotonic() - rep.last_ok_mono, 3)
        return ReplicaInfo(
            name=rep.name, target=rep.target, state=rep.health.state,
            scrapes=rep.scrapes, failures=rep.failures,
            last_scrape_age_s=age,
            queue_depth=metric_total(rep.metrics, "serving_queue_depth"),
            active_slots=metric_total(rep.metrics, "serving_active_slots"),
            prefix_hit_rate=hit_rate,
            goodput_ratio=metric_total(rep.metrics, "goodput_ratio"),
            ttft_p99_ms=ttft, tpot_p99_ms=tpot, active_alerts=alerts)

    def replicas(self) -> List[ReplicaInfo]:
        with self._lock:
            return [self._replica_info(r) for r in self._reps.values()]

    def healthy(self) -> List[ReplicaInfo]:
        return [r for r in self.replicas() if r.state == "healthy"]

    def best_for_prefix(self, counters: Sequence[str] = (
            "prefix_cache_hit_tokens_total",)) -> Optional[ReplicaInfo]:
        """The replica a prefix-cache-aware router should prefer.

        Ranking contract, in order:

        1. **Reporting beats absent.**  A replica where every named
           counter is ABSENT from the scrape ranks below any replica
           that reports one — even a reported zero.  A restarted
           replica hasn't registered the counter family yet, so its
           cache heat is UNKNOWN, not zero; before this rule a fresh
           replica sorted EQUAL to a known-cold one and the
           queue-depth tie-break could route prefix traffic at a cache
           that provably holds nothing.  (When every candidate is
           absent — a whole-fleet restart — the rule is vacuous and
           ranking falls through to the tie-break.)
        2. **Higher summed hit counters win** among reporting replicas
           — the ``kvreuse`` counters make cache residency measurable
           without shipping radix-tree contents.  Note this is a
           GLOBAL heat signal (total hit tokens, not per-prefix); the
           serving router's ``PrefixSketch`` upgrades it to per-prefix
           placement.
        3. **Ties break toward the shallower queue.**

        Only routable (healthy/degraded) replicas are considered;
        never returns a ``down`` replica."""
        with self._lock:
            cands = [r for r in self._reps.values()
                     if r.health.state in ("healthy", "degraded")]
            if not cands:
                return None

            def rank(r: _Rep):
                totals = [metric_total(r.metrics, c) for c in counters]
                known = [t for t in totals if t is not None]
                return (1 if known else 0, sum(known),
                        -(metric_total(r.metrics, "serving_queue_depth")
                          or 0.0))

            best = max(cands, key=rank)
            return self._replica_info(best)

    def _total_queue_locked(self) -> float:
        return sum(
            metric_total(r.metrics, "serving_queue_depth") or 0.0
            for r in self._reps.values() if r.health.state != "down")

    def total_queue_depth(self) -> float:
        """Summed queue depth across non-down replicas (a down
        replica's last-known depth is not real backlog a router can
        drain)."""
        with self._lock:
            return self._total_queue_locked()

    # -- cross-replica request traces ----------------------------------
    def fetch_tracez(self) -> Dict[str, Optional[dict]]:
        """Fetch ``/tracez?full=1`` from every non-down replica (on
        demand, NOT in the background scrape loop — span payloads are
        orders of magnitude bigger than a metrics scrape and only a
        tail-latency investigation needs them).  Fetches run
        CONCURRENTLY over the same bounded-pool pattern as
        ``scrape_once``: one blackholed host costing a full
        ``timeout_s`` must not stall the fleet ``/tracez`` response by
        N × timeout — the outage window is exactly when the stitched
        view is wanted."""
        with self._lock:
            reps = [(r.name, r.target) for r in self._reps.values()
                    if r.health.state != "down"]

        def fetch_one(target: str) -> Optional[dict]:
            try:
                code, body = self._fetch(target, "/tracez?full=1")
                return json.loads(body.decode()) if code == 200 else None
            except Exception:
                return None

        if len(reps) <= 1:
            return {name: fetch_one(target) for name, target in reps}
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(max_workers=min(8, len(reps)),
                                thread_name_prefix="dstpu-tracez") as pool:
            futs = {name: pool.submit(fetch_one, target)
                    for name, target in reps}
            return {name: f.result() for name, f in futs.items()}

    def stitched_traces(self) -> dict:
        """The fleet ``/tracez`` payload: every replica's retained
        traces merged by trace id (see :func:`stitch_tracez`) — a
        request that hopped replicas under one propagated
        ``traceparent`` reads as a single span timeline."""
        return stitch_tracez(self.fetch_tracez())

    # -- merged views ---------------------------------------------------
    def _per_replica_metrics(self) -> Dict[str, dict]:
        with self._lock:
            return {r.name: r.metrics for r in self._reps.values()
                    if r.metrics is not None}

    def merged(self) -> Tuple[dict, List[dict]]:
        merged, issues = merge_metrics(self._per_replica_metrics())
        fresh = [i for i in issues if i["kind"] == "bucket_schema"]
        if fresh:
            self._m_schema_conflicts.inc(len(fresh))
        return merged, issues

    def federated_prometheus(self) -> str:
        """The aggregator's ``/metrics`` body: its OWN registry (the
        ``fleet_*`` plane) and every replica's families with ``replica``
        labels, merged FAMILY-WISE — a name living in both (the
        aggregator process exports ``goodput_ratio``/``alerts_total``
        too, since it imports the telemetry package) gets ONE ``TYPE``
        block holding the aggregator's unlabeled samples alongside the
        replica-labeled ones, so neither side shadows the other."""
        fed, _ = federate_metrics(self._per_replica_metrics())
        combined = self.registry.snapshot()
        for name, entry in fed.items():
            cur = combined.get(name)
            if cur is None:
                combined[name] = entry
            elif cur["type"] == entry["type"]:
                cur["samples"] = list(cur["samples"]) + entry["samples"]
            # type conflict: keep the aggregator's own family
        return _registry.render_prometheus_snapshot(combined)

    def fleetz(self) -> dict:
        """The ``/fleetz`` payload: per-replica table + fleet rollups
        (counter sums, gauge min/max/sum, SLO attainment, fleet-wide
        tail latencies off merged histograms via the one
        ``registry.pct`` convention)."""
        merged, issues = self.merged()
        rows = self.replicas()
        counters = {name: round(sum(s["value"] for s in e["samples"]), 6)
                    for name, e in merged.items()
                    if e["type"] == "counter"}
        gauges = {name: {
            "min": min((s["min"] for s in e["samples"]
                        if s["min"] is not None), default=None),
            "max": max((s["max"] for s in e["samples"]
                        if s["max"] is not None), default=None),
            "sum": round(sum(s["sum"] for s in e["samples"]), 6)}
            for name, e in merged.items() if e["type"] == "gauge"}
        met = counters.get("serving_slo_met_total")
        viol = counters.get("serving_slo_violations_total")
        slo = None
        if met is not None or viol is not None:
            met, viol = met or 0.0, viol or 0.0
            slo = {"met": met, "violated": viol,
                   "attainment": None if met + viol == 0
                   else round(met / (met + viol), 6)}
        ttft_h = family_histogram(merged.get("serving_ttft_seconds"))
        tpot_h = family_histogram(merged.get("serving_tpot_ms"))
        qwait_h = family_histogram(merged.get("serving_queue_wait_ms"))
        ttft_p99 = None if ttft_h is None else histogram_quantile(
            ttft_h, 0.99)
        tpot_p99 = None if tpot_h is None else histogram_quantile(
            tpot_h, 0.99)
        qwait_p99 = None if qwait_h is None else histogram_quantile(
            qwait_h, 0.99)
        states = {s: sum(1 for r in rows if r.state == s)
                  for s in HEALTH_STATES}
        # fleet goodput: wall-weighted mean of per-replica ratios when
        # the wall gauge is exported, plain mean otherwise
        ratios = [(r.goodput_ratio, g) for r, g in (
            (row, self._wall_for(row.name)) for row in rows)
            if r.goodput_ratio is not None]
        goodput = None
        if ratios:
            if all(g is not None and g > 0 for _, g in ratios):
                goodput = sum(r * g for r, g in ratios) \
                    / sum(g for _, g in ratios)
            else:
                goodput = sum(r for r, _ in ratios) / len(ratios)
            goodput = round(goodput, 6)
        return {
            "t": time.time(),
            "rounds": self._rounds,
            "replicas": {r.name: r.as_dict() for r in rows},
            "fleet": {
                "states": states,
                "total_queue_depth": self.total_queue_depth(),
                "active_slots": sum(r.active_slots or 0 for r in rows),
                "goodput_ratio": goodput,
                "slo": slo,
                "ttft_p99_ms": None if ttft_p99 is None
                else round(ttft_p99 * 1e3, 3),
                "tpot_p99_ms": None if tpot_p99 is None
                else round(tpot_p99, 3),
                # fleet-wide queue wait off the merged serving_queue_wait_ms
                # histogram: admission pressure a router can actually see
                "queue_wait_p99_ms": None if qwait_p99 is None
                else round(qwait_p99, 3),
                "counters": counters,
                "gauges": gauges,
            },
            "issues": issues,
        }

    def _wall_for(self, name: str) -> Optional[float]:
        with self._lock:
            rep = self._reps.get(name)
        if rep is None:
            return None
        return metric_total(rep.metrics, "goodput_wall_seconds_total")


# ---------------------------------------------------------------------------
# the /fleetz HTTP surface
# ---------------------------------------------------------------------------

class _FleetHandler(BaseHTTPRequestHandler):
    view: FleetView = None          # type: ignore[assignment]

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):               # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        try:
            if path == "/fleetz":
                self._send(200, json.dumps(self.view.fleetz()).encode(),
                           "application/json")
            elif path == "/metrics":
                _registry.run_collectors()
                self._send(200, self.view.federated_prometheus().encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                rows = self.view.replicas()
                payload = {
                    "ok": True,
                    "replicas": {s: sum(1 for r in rows if r.state == s)
                                 for s in HEALTH_STATES}}
                self._send(200, json.dumps(payload).encode(),
                           "application/json")
            elif path == "/tracez":
                # cross-replica request traces, stitched by trace id
                self._send(200,
                           json.dumps(self.view.stitched_traces()).encode(),
                           "application/json")
            else:
                self._send(404, b"not found: try /fleetz /metrics "
                                b"/healthz /tracez\n", "text/plain")
        except BrokenPipeError:
            pass
        except Exception as e:      # a scrape must never kill the plane
            try:
                self._send(500, repr(e).encode(), "text/plain")
            except Exception:
                pass

    def log_message(self, fmt, *args):
        logger.debug("fleet server: " + fmt % args)


class FleetServer:
    """HTTP server over a :class:`FleetView`: ``/fleetz`` (the table),
    ``/metrics`` (federated), ``/healthz`` (aggregator liveness),
    ``/tracez`` (cross-replica request traces stitched by trace id)."""

    def __init__(self, view: FleetView, port: int = 0,
                 host: str = "127.0.0.1"):
        self.view = view
        self.host = host
        self._requested_port = int(port)
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self.host}:{self.port}" if self._server else None

    def start(self) -> "FleetServer":
        if self._server is not None:
            return self
        handler = type("_BoundFleetHandler", (_FleetHandler,),
                       {"view": self.view})
        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dstpu-fleetz",
            daemon=True)
        self._thread.start()
        logger.info(f"fleet aggregator serving /fleetz /metrics /healthz "
                    f"/tracez on {self.url}")
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None
