"""Crash flight recorder: a per-rank ring buffer dumped on death.

A crashed worker today leaves nothing: the metrics exit dump needs a
clean ``atexit``, the trace file needs tracing enabled, and the launcher
only sees an exit code or a stale heartbeat.  The flight recorder keeps
a small always-on in-memory ring of the last N completed spans (via the
tracer's span-observer hook — recording works with Chrome tracing OFF),
the last N ``deepspeed_tpu`` log records (a ``logging.Handler``), and
recent metric deltas (counter movement between throttled ``mark()``
calls — wired off ``goodput.note_step`` and the heartbeat), and writes
``<metrics_dir>/flight_<rank>.json`` from:

- ``atexit`` (clean exits — the dump doubles as a "last run" record),
- SIGTERM / SIGABRT handlers (the launcher killing a stale worker, a
  preemption, an XLA abort) — which ALSO flush the per-rank metrics
  snapshot (``registry.flush_exit_dump``) that a signal death would
  otherwise lose, then re-deliver the signal so exit semantics hold,
- an unhandled-exception hook (``sys.excepthook`` chain) that captures
  the traceback into the dump.

Armed automatically when ``DSTPU_METRICS_DIR`` is set (the launcher's
``--metrics_dir``); ``launcher/runner.py`` pretty-prints the newest dump
when it restarts a dead worker.  Everything here is best-effort: a
failing dump must never mask the original death.
"""
from __future__ import annotations

import atexit
import json
import logging as _logging
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque
from typing import Optional

from ..utils.logging import logger
from . import registry as _registry

__all__ = ["FlightRecorder", "get_recorder", "maybe_install", "mark",
           "dump", "pretty", "add_sigterm_hook", "sigterm_managed",
           "FLIGHT_DIR_ENV"]

# separate override for the rare case flight dumps should land away from
# the metrics dir; defaults to DSTPU_METRICS_DIR
FLIGHT_DIR_ENV = "DSTPU_FLIGHT_DIR"

_SPAN_RING = 256
_LOG_RING = 200
_DELTA_RING = 120
_MARK_MIN_INTERVAL_S = 1.0


class _RingLogHandler(_logging.Handler):
    def __init__(self, ring: deque):
        super().__init__()
        self._ring = ring

    def emit(self, record) -> None:
        try:
            self._ring.append({
                "t": record.created,
                "level": record.levelname,
                "msg": record.getMessage()[:2000],
            })
        except Exception:
            pass


class FlightRecorder:
    def __init__(self, directory: str):
        self.directory = directory
        self._t0_mono = time.monotonic()
        self._t0_unix = time.time()
        self.spans: deque = deque(maxlen=_SPAN_RING)
        self.logs: deque = deque(maxlen=_LOG_RING)
        self.deltas: deque = deque(maxlen=_DELTA_RING)
        # RLock: a SIGTERM landing inside mark() must not deadlock the
        # handler's own registry walk
        self._mark_lock = threading.RLock()
        self._last_mark = 0.0
        self._last_counters: dict = {}
        self._dumped_reasons: set = set()
        self._log_handler = _RingLogHandler(self.logs)

    # -- span observer protocol (trace.add_span_observer) --------------
    def span_enter(self, name: str) -> None:
        pass

    def span_exit(self, name: str, dur_s: float, args) -> None:
        self.spans.append({"t": time.time(), "name": name,
                           "dur_ms": round(dur_s * 1e3, 3),
                           **({"args": args} if args else {})})

    # -- metric deltas ---------------------------------------------------
    def _counter_totals(self) -> dict:
        reg = _registry.get_registry()
        out = {}
        with reg._lock:
            metrics = list(reg._metrics.values())
        for m in metrics:
            if m.kind == "counter":
                out[m.name] = sum(c.value for _, c in m.samples())
        return out

    def mark(self, label: str = "", context: Optional[dict] = None) -> None:
        """Record counter movement since the previous mark (throttled to
        one per second — wired off ``goodput.note_step`` and the
        heartbeat, so a busy loop costs a dict diff per second).

        ``context`` (small JSON-ables — e.g. the serving loop's in-flight
        request uids) is stored on the delta entry, so a postmortem can
        name WHOSE work the counters were moving for at crash time."""
        now = time.monotonic()
        with self._mark_lock:
            if now - self._last_mark < _MARK_MIN_INTERVAL_S:
                return
            self._last_mark = now
            cur = self._counter_totals()
            prev, self._last_counters = self._last_counters, cur
        delta = {k: round(v - prev.get(k, 0.0), 6)
                 for k, v in cur.items() if v != prev.get(k, 0.0)}
        if delta:
            entry = {"t": time.time(), "label": label, "deltas": delta}
            if context:
                entry["ctx"] = context
            self.deltas.append(entry)

    # -- dumping ---------------------------------------------------------
    def dump(self, reason: str, exc: Optional[BaseException] = None
             ) -> Optional[str]:
        """Write the flight dump; returns the path (None on failure).

        A clean-exit (``atexit``) dump never overwrites a crash dump
        already written this process: the excepthook fires before
        interpreter shutdown, and the forensics of the crash are the
        valuable copy."""
        if reason == "atexit" and self._dumped_reasons:
            return None
        try:
            from . import goodput
            from ..utils import heartbeat

            _registry.run_collectors()
            hb_age = heartbeat.last_beat_age()
            payload = {
                "reason": reason,
                "time_unix": time.time(),
                "rank": _registry._rank(),
                "pid": os.getpid(),
                "argv": list(sys.argv),
                "uptime_s": round(time.monotonic() - self._t0_mono, 3),
                "heartbeat_age_s":
                    None if hb_age is None else round(hb_age, 3),
                "goodput": goodput.summary(),
                "spans": list(self.spans),
                "logs": list(self.logs),
                "metric_deltas": list(self.deltas),
                "metrics": _registry.get_registry().snapshot(),
            }
            # what was alerting + what was slow at death: the anomaly
            # engine's active/recent alerts and the attribution plane's
            # last per-executable snapshot ride every dump, so a
            # postmortem answers both without re-running the workload
            try:
                from . import anomaly as _anomaly

                a = _anomaly.get_engine().status()
                if a["active"] or a["recent"]:
                    payload["alerts"] = {"active": a["active"],
                                         "recent": a["recent"]}
            except Exception:
                pass
            try:
                from . import attribution as _attribution

                snap = _attribution.snapshot()
                if snap.get("rows"):
                    payload["attribution"] = snap
            except Exception:
                pass
            # retained request traces (telemetry/reqtrace.py): the
            # tail-retention index (promoted SLO-violating / alert-
            # coincident summaries first) rides the dump, so a crash
            # mid-load names not just WHICH uids were in flight but
            # what each outlier's span walls looked like
            try:
                from . import reqtrace as _reqtrace

                idx = _reqtrace.flight_index()
                if idx:
                    payload["reqtrace"] = idx
            except Exception:
                pass
            if exc is not None:
                payload["exception"] = {
                    "type": type(exc).__name__,
                    "value": str(exc)[:4000],
                    "traceback": traceback.format_exception(
                        type(exc), exc, exc.__traceback__)[-50:],
                }
            path = os.path.join(
                self.directory, f"flight_{_registry._rank()}.json")
            os.makedirs(self.directory, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as fh:
                json.dump(payload, fh, indent=1, default=str)
            os.replace(tmp, path)
            self._dumped_reasons.add(reason.split(":")[0])
            return path
        except Exception:
            return None   # forensics must never mask the original death


_recorder: Optional[FlightRecorder] = None
_prev_handlers: dict = {}
_prev_excepthook = None
_atexit_done = False


def get_recorder() -> Optional[FlightRecorder]:
    return _recorder


def disarm() -> None:
    """Drop the recorder so dumps become no-ops (installed signal/atexit
    hooks stay but fall through).  The LAUNCHER calls this: with
    ``DSTPU_METRICS_DIR`` exported operator-side, the import-armed
    recorder in the launcher process would otherwise overwrite worker
    rank 0's forensics at launcher exit."""
    global _recorder
    if _recorder is not None:
        try:
            from . import trace as _trace

            _trace.remove_span_observer(_recorder)
            logger.removeHandler(_recorder._log_handler)
        except Exception:
            pass
    _recorder = None


def mark(label: str = "", context: Optional[dict] = None) -> None:
    if _recorder is not None:
        _recorder.mark(label, context)


def dump(reason: str, exc: Optional[BaseException] = None) -> Optional[str]:
    return _recorder.dump(reason, exc) if _recorder is not None else None


_sigterm_hooks: list = []


def sigterm_managed() -> bool:
    """True while the flight recorder's handler owns SIGTERM — the
    signal an :func:`add_sigterm_hook` hook will actually run under.
    Subsystems that want a SIGTERM side effect (the
    ``AsyncCheckpointManager`` preemption save) check this first:
    when the recorder owns the signal they must REGISTER A HOOK, not
    ``signal.signal`` over the handler (which would silently drop the
    dump/flush/drain chain — and every other registered hook)."""
    try:
        return signal.getsignal(signal.SIGTERM) is _on_signal
    except (ValueError, OSError):
        return False


def add_sigterm_hook(fn):
    """Run ``fn()`` on SIGTERM BEFORE the flight dump — the graceful-
    drain seam: a replica being terminated by the launcher finishes its
    in-flight requests (``ContinuousBatcher.drain``), then the dump
    snapshots the drained state.  SIGTERM only: SIGABRT means the
    process is wedged, and a drain could hang the abort.  Hooks are
    best-effort (exceptions swallowed — forensics must never mask the
    shutdown); returns a zero-arg remover."""
    _sigterm_hooks.append(fn)

    def remove():
        if fn in _sigterm_hooks:
            _sigterm_hooks.remove(fn)
    return remove


def _on_signal(signum, frame):
    name = signal.Signals(signum).name if signum in list(signal.Signals) \
        else str(signum)
    if signum == signal.SIGTERM:
        for fn in list(_sigterm_hooks):
            try:
                fn()
            except Exception:
                pass
    dump(reason=f"signal:{name}")
    # the satellite fix: metrics must survive the launcher's SIGTERM
    # (atexit never runs under default signal death)
    _registry.flush_exit_dump()
    prev = _prev_handlers.get(signum)
    if callable(prev):
        prev(signum, frame)
    elif prev == signal.SIG_IGN:
        return
    else:
        # restore default disposition and re-deliver so the exit status
        # still says "killed by signal" (the launcher keys off it)
        signal.signal(signum, signal.SIG_DFL)
        os.kill(os.getpid(), signum)


def _on_exception(exc_type, exc, tb):
    try:
        dump(reason="exception", exc=exc)
        _registry.flush_exit_dump()
    finally:
        (_prev_excepthook or sys.__excepthook__)(exc_type, exc, tb)


def _on_atexit():
    dump(reason="atexit")


def maybe_install(directory: Optional[str] = None) -> Optional[FlightRecorder]:
    """Arm the flight recorder when a dump directory is configured
    (``DSTPU_FLIGHT_DIR``, falling back to ``DSTPU_METRICS_DIR``).
    Idempotent; called on telemetry import.  Returns the recorder."""
    global _recorder, _prev_excepthook, _atexit_done
    directory = directory or os.environ.get(FLIGHT_DIR_ENV) \
        or os.environ.get(_registry.METRICS_DIR_ENV)
    if not directory:
        return None
    if _recorder is not None:
        _recorder.directory = directory
        return _recorder
    _recorder = FlightRecorder(directory)

    from . import trace as _trace

    _trace.add_span_observer(_recorder)
    logger.addHandler(_recorder._log_handler)
    if not _atexit_done:
        atexit.register(_on_atexit)
        _atexit_done = True
    if _prev_excepthook is None:
        _prev_excepthook = sys.excepthook
        sys.excepthook = _on_exception
    for signum in (signal.SIGTERM, signal.SIGABRT):
        try:
            # only from the main thread; a custom handler someone already
            # installed is chained, not replaced
            _prev_handlers[signum] = signal.getsignal(signum)
            signal.signal(signum, _on_signal)
        except (ValueError, OSError):     # non-main thread / exotic env
            _prev_handlers.pop(signum, None)
    return _recorder


# ----------------------------------------------------------------------
# pretty-printing (the launcher's postmortem view)
# ----------------------------------------------------------------------
def pretty(path_or_payload, max_spans: int = 8, max_logs: int = 8) -> str:
    """Human-readable postmortem of a flight dump — what the launcher
    prints when it restarts a dead worker."""
    if isinstance(path_or_payload, str):
        with open(path_or_payload) as fh:
            p = json.load(fh)
    else:
        p = path_or_payload
    t_dump = p.get("time_unix", 0.0)
    when = time.strftime("%Y-%m-%dT%H:%M:%S", time.localtime(t_dump))
    lines = [f"flight dump: rank {p.get('rank')} pid {p.get('pid')} "
             f"reason={p.get('reason')} at {when} "
             f"(uptime {p.get('uptime_s')}s)"]
    gp = p.get("goodput") or {}
    if gp.get("last_step_age_s") is not None:
        lines.append(f"  last step {gp['last_step_age_s']}s before dump; "
                     f"goodput_ratio={gp.get('goodput_ratio')}")
    if p.get("heartbeat_age_s") is not None:
        lines.append(f"  last heartbeat {p['heartbeat_age_s']}s before dump")
    exc = p.get("exception")
    if exc:
        lines.append(f"  died on {exc['type']}: {exc['value']}")
        for tb_line in exc.get("traceback", [])[-3:]:
            lines.append("    " + tb_line.rstrip().replace("\n", "\n    "))
    spans = p.get("spans", [])[-max_spans:]
    if spans:
        lines.append(f"  last {len(spans)} spans:")
        for s in spans:
            ago = round(t_dump - s["t"], 3)
            args = f" {s['args']}" if s.get("args") else ""
            lines.append(f"    -{ago}s {s['name']} "
                         f"{s['dur_ms']}ms{args}")
    logs = p.get("logs", [])[-max_logs:]
    if logs:
        lines.append(f"  last {len(logs)} log records:")
        for r in logs:
            ago = round(t_dump - r["t"], 3)
            lines.append(f"    -{ago}s [{r['level']}] {r['msg']}")
    deltas = p.get("metric_deltas", [])[-3:]
    if deltas:
        lines.append("  recent metric deltas:")
        for d in deltas:
            ago = round(t_dump - d["t"], 3)
            ctx = f" ctx={d['ctx']}" if d.get("ctx") else ""
            lines.append(f"    -{ago}s {d.get('label', '')} "
                         f"{d['deltas']}{ctx}")
    # in-flight request attribution: the last serving mark's context and
    # any span args carrying uids name the requests on the pool at death
    in_flight = None
    for d in reversed(p.get("metric_deltas", [])):
        if (d.get("ctx") or {}).get("uids"):
            in_flight = d["ctx"]["uids"]
            break
    if in_flight is None:
        for s in reversed(p.get("spans", [])):
            if (s.get("args") or {}).get("uids"):
                in_flight = s["args"]["uids"]
                break
    if in_flight:
        lines.append(f"  in-flight request uids at last mark: {in_flight}")
    # retained request traces: the SLO-violating (or alert-coincident)
    # outliers with their per-phase span walls — "why was this one
    # slow" without leaving the postmortem
    viol = [s for s in (p.get("reqtrace") or {}).get("retained", [])
            if s.get("retained") in ("slo_violation", "alert")]
    if viol:
        lines.append(f"  retained SLO-violating traces "
                     f"({len(viol)}, newest first):")
        for s in viol[:4]:
            walls = " ".join(f"{k}={v}ms" for k, v in
                             (s.get("span_walls_ms") or {}).items())
            tpot = s.get("tpot_ms")
            lines.append(
                f"    {s['trace_id'][:12]}… uid={s.get('uid')} "
                f"[{s.get('retained')}] ttft={s.get('ttft_ms')}ms "
                f"tpot={'-' if tpot is None else tpot}ms "
                f"n_out={s.get('n_out')} {walls}")
    # what was firing: active alerts first, then recent transitions —
    # the "was anything alerting when it died" question
    alerts = p.get("alerts") or {}
    act = alerts.get("active") or []
    if act:
        lines.append(f"  ACTIVE alerts at dump ({len(act)}):")
        for a in act:
            ago = round(t_dump - a.get("t", t_dump), 3)
            lines.append(
                f"    {a['rule']} firing since -{ago}s "
                f"value={a.get('value')} threshold={a.get('threshold')} "
                f"{a.get('detail') or ''}")
    elif alerts.get("recent"):
        last = alerts["recent"][-1]
        ago = round(t_dump - last.get("t", t_dump), 3)
        lines.append(f"  no active alerts; last transition -{ago}s: "
                     f"{last['rule']} {last['state']}")
    # what was slow: the attribution plane's measured executables with
    # their roofline verdicts (slowest first, as snapshotted)
    attr_rows = [r for r in (p.get("attribution") or {}).get("rows", [])
                 if r.get("measured_ms") is not None]
    if attr_rows:
        lines.append("  attribution (measured executables, slowest first):")
        for r in attr_rows[:5]:
            mfu = f" mfu={r['mfu']:.4f}" if r.get("mfu") is not None else ""
            bw = f" bw={r['bw_frac']:.4f}" \
                if r.get("bw_frac") is not None else ""
            lines.append(f"    {r['site']:<32} {r['measured_ms']}ms "
                         f"{r['verdict']}{mfu}{bw}")
    key = {}
    for name in ("train_steps_total", "serving_decode_ticks_total",
                 "serving_requests_completed_total", "xla_recompiles_total",
                 "heartbeat_beats_total"):
        entry = (p.get("metrics") or {}).get(name)
        if entry:
            key[name] = sum(s.get("value", 0) for s in entry["samples"])
    if key:
        lines.append("  key counters: " + " ".join(
            f"{k}={v:g}" for k, v in key.items()))
    return "\n".join(lines)


def newest_dump(directory: str,
                since: Optional[float] = None) -> Optional[str]:
    """Flight dump to show for a failed run (None when there is none) —
    the launcher's collection hook.

    ``since`` (a unix mtime) STRICTLY drops dumps from a previous
    restart attempt — a stale dump presented as this failure's
    postmortem would send the operator debugging the wrong death.
    Among current dumps, a CRASH dump (exception / SIGABRT) wins over
    ``signal:SIGTERM`` ones even when older: when one rank dies, the
    launcher SIGTERMs the healthy rest, whose dumps land LATER —
    newest-by-mtime alone would show a victim, not the cause."""
    try:
        cands = [os.path.join(directory, f) for f in os.listdir(directory)
                 if f.startswith("flight_") and f.endswith(".json")]
        if since is not None:
            cands = [p for p in cands if os.path.getmtime(p) >= since]
        if not cands:
            return None
        cands.sort(key=os.path.getmtime, reverse=True)
        for path in cands:
            try:
                with open(path) as fh:
                    if json.load(fh).get("reason") != "signal:SIGTERM":
                        return path
            except Exception:
                continue
        return cands[0]
    except OSError:
        return None
