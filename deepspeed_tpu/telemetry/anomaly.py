"""Anomaly detectors over the live telemetry: structured, thresholded
alerts.

The registry exports raw counters for a human to eyeball; admission
control and load shedding (ROADMAP item 2) need the telemetry plane to
*raise signals*.  This module runs rolling detectors over registry
series and emits structured alert events:

- **recompile storm** — ``xla_recompiles_total`` (the recompile
  watchdog) moved ≥ N in the window: a hot loop is recompiling.
- **slo_burn** — TTFT/TPOT SLO burn rate (the PR-7 retire-time
  tagging): violations / retirements in the window above the budget.
- **queue_runaway** — ``serving_queue_depth`` monotonically climbing
  across K observations above a floor: arrivals outrun service.
- **acceptance_collapse** — ``specdec_acceptance_rate`` under the
  floor while verify ticks are still being paid.
- **goodput_drop** — ``goodput_ratio`` under the floor on a warmed-up
  process.
- **attribution_drift** — a per-executable roofline verdict flipped
  (e.g. ``hbm-bound`` → ``overhead-bound``): the executable's
  character changed even if throughput hasn't visibly regressed yet.

Every fire/clear transition lands in FOUR places: the
``alerts_total{rule}`` counter + ``alerts_firing{rule}`` gauge, the
``/alertz`` endpoint (active + recent events), a ``logger.warning``
(which rides the flight recorder's log ring, so a crash dump shows
what was alerting — the dump also embeds :func:`status` directly), and
every :func:`subscribe` callback — the explicit seam an admission
controller / load shedder consumes.

Detectors are hysteresis state machines (``fire_after`` consecutive
bad evaluations to fire, ``clear_after`` good ones to clear), so a
single noisy sample neither pages nor flaps.  Thresholds come from
``DSTPU_ALERT_*`` env knobs (see each detector).  Evaluation is
throttled to ~1/s and rides ``goodput.note_step`` plus every registry
scrape — no extra thread.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from ..utils.logging import logger
from . import registry as _registry

__all__ = [
    "Series", "Detector", "RecompileStormDetector", "SloBurnDetector",
    "QueueRunawayDetector", "AcceptanceCollapseDetector",
    "GoodputDropDetector", "AttributionDriftDetector",
    "LossSpikeDetector", "GradNormExplosionDetector", "AnomalyEngine",
    "get_engine", "observe", "subscribe", "active", "recent", "status",
    "install",
]


def _envf(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _envi(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


class Series:
    """Bounded rolling (t, value) samples of one registry series."""

    def __init__(self, maxlen: int = 240):
        self._xs: deque = deque(maxlen=maxlen)

    def add(self, t: float, v: float) -> None:
        self._xs.append((float(t), float(v)))

    def __len__(self) -> int:
        return len(self._xs)

    def last(self) -> Optional[float]:
        return self._xs[-1][1] if self._xs else None

    def delta(self, window_s: float, now: Optional[float] = None
              ) -> Optional[float]:
        """value movement across the trailing window: last sample minus
        the OLDEST sample inside ``[now - window_s, now]``.  None with
        fewer than two in-window samples (a delta needs an interval)."""
        if len(self._xs) < 2:
            return None
        now = self._xs[-1][0] if now is None else now
        lo = now - window_s
        inside = [(t, v) for t, v in self._xs if t >= lo]
        if len(inside) < 2:
            return None
        return inside[-1][1] - inside[0][1]

    def increasing_run(self, k: int) -> bool:
        """True when the last ``k`` consecutive steps (k+1 samples) are
        STRICTLY increasing."""
        if len(self._xs) < k + 1:
            return False
        tail = [v for _, v in list(self._xs)[-(k + 1):]]
        return all(b > a for a, b in zip(tail, tail[1:]))

    def tail(self, n: int) -> List[float]:
        """The last ``n`` values (fewer when the series is shorter)."""
        return [v for _, v in list(self._xs)[-n:]]

    def clear(self) -> None:
        self._xs.clear()


def _metric_total(name: str) -> Optional[float]:
    """Sum of a registry metric's samples WITHOUT get-or-create: a
    reader must never pre-register a name with the wrong labelset (the
    later real declaration would raise)."""
    reg = _registry.get_registry()
    with reg._lock:
        m = reg._metrics.get(name)
    if m is None:
        return None
    return sum(c.value for _, c in m.samples())


class Detector:
    """Hysteresis state machine over one violation predicate.

    Subclasses implement :meth:`check` returning a violation dict
    ``{"value", "threshold", "detail"}`` or None.  ``step`` turns
    consecutive check results into at most one fire event and one clear
    event per transition."""

    name = "detector"
    fire_after = 1
    clear_after = 3

    def __init__(self):
        self.firing = False
        self._bad = 0
        self._good = 0
        self._last_violation: Optional[dict] = None

    def check(self, engine: "AnomalyEngine", now: float) -> Optional[dict]:
        raise NotImplementedError

    def thresholds(self) -> dict:
        return {}

    def step(self, engine: "AnomalyEngine", now: float) -> List[dict]:
        violation = self.check(engine, now)
        events: List[dict] = []
        if violation is not None:
            self._bad += 1
            self._good = 0
            self._last_violation = violation
            if not self.firing and self._bad >= self.fire_after:
                self.firing = True
                events.append(self._event("firing", now, violation))
        else:
            self._good += 1
            self._bad = 0
            if self.firing and self._good >= self.clear_after:
                self.firing = False
                events.append(self._event(
                    "cleared", now, self._last_violation or {}))
        return events

    def _event(self, state: str, now: float, violation: dict) -> dict:
        return {"rule": self.name, "state": state, "t": now,
                "value": violation.get("value"),
                "threshold": violation.get("threshold"),
                "detail": violation.get("detail", {})}

    def reset(self) -> None:
        """Back to the quiescent state WITHOUT emitting a clear event —
        the TrainGuard calls this after a rollback (the pre-rollback
        samples are no longer evidence about the restored state)."""
        self.firing = False
        self._bad = 0
        self._good = 0
        self._last_violation = None


class RecompileStormDetector(Detector):
    """``xla_recompiles_total`` moved ≥ ``n`` inside ``window_s``.
    Knobs: ``DSTPU_ALERT_RECOMPILE_N`` (3),
    ``DSTPU_ALERT_RECOMPILE_WINDOW_S`` (60)."""

    name = "recompile_storm"
    fire_after = 1
    clear_after = 2

    def __init__(self, n: Optional[int] = None,
                 window_s: Optional[float] = None):
        super().__init__()
        self.n = _envi("DSTPU_ALERT_RECOMPILE_N", 3) if n is None else n
        self.window_s = _envf("DSTPU_ALERT_RECOMPILE_WINDOW_S", 60.0) \
            if window_s is None else window_s

    def thresholds(self) -> dict:
        return {"n": self.n, "window_s": self.window_s}

    def check(self, engine, now):
        d = engine.series["recompiles"].delta(self.window_s, now)
        if d is not None and d >= self.n:
            return {"value": d, "threshold": self.n,
                    "detail": {"window_s": self.window_s}}
        return None


class SloBurnDetector(Detector):
    """SLO burn rate: violations / retirements inside the window above
    ``burn`` with at least ``min_events`` retirements (a 1-of-1
    violation is noise, not a burn).  Knobs: ``DSTPU_ALERT_SLO_BURN``
    (0.5), ``DSTPU_ALERT_SLO_WINDOW_S`` (60),
    ``DSTPU_ALERT_SLO_MIN_EVENTS`` (8)."""

    name = "slo_burn"
    fire_after = 1
    clear_after = 3

    def __init__(self, burn: Optional[float] = None,
                 window_s: Optional[float] = None,
                 min_events: Optional[int] = None):
        super().__init__()
        self.burn = _envf("DSTPU_ALERT_SLO_BURN", 0.5) \
            if burn is None else burn
        self.window_s = _envf("DSTPU_ALERT_SLO_WINDOW_S", 60.0) \
            if window_s is None else window_s
        self.min_events = _envi("DSTPU_ALERT_SLO_MIN_EVENTS", 8) \
            if min_events is None else min_events

    def thresholds(self) -> dict:
        return {"burn": self.burn, "window_s": self.window_s,
                "min_events": self.min_events}

    @staticmethod
    def burn_rate(met_delta: Optional[float],
                  viol_delta: Optional[float]) -> Optional[tuple]:
        """(burn_rate, events) over one window; None when either series
        is absent.  Pure — the unit-test fixture surface."""
        if met_delta is None or viol_delta is None:
            return None
        events = met_delta + viol_delta
        if events <= 0:
            return (0.0, 0.0)
        return (viol_delta / events, events)

    def check(self, engine, now):
        br = self.burn_rate(
            engine.series["slo_met"].delta(self.window_s, now),
            engine.series["slo_violations"].delta(self.window_s, now))
        if br is None:
            return None
        rate, events = br
        if events >= self.min_events and rate >= self.burn:
            return {"value": rate, "threshold": self.burn,
                    "detail": {"events": events,
                               "window_s": self.window_s}}
        return None


class QueueRunawayDetector(Detector):
    """``serving_queue_depth`` strictly increased across ``run``
    consecutive observations AND sits ≥ ``min_depth``.  Knobs:
    ``DSTPU_ALERT_QUEUE_RUN`` (5), ``DSTPU_ALERT_QUEUE_DEPTH`` (32)."""

    name = "queue_runaway"
    fire_after = 1
    clear_after = 2

    def __init__(self, run: Optional[int] = None,
                 min_depth: Optional[float] = None):
        super().__init__()
        self.run = _envi("DSTPU_ALERT_QUEUE_RUN", 5) if run is None else run
        self.min_depth = _envf("DSTPU_ALERT_QUEUE_DEPTH", 32.0) \
            if min_depth is None else min_depth

    def thresholds(self) -> dict:
        return {"run": self.run, "min_depth": self.min_depth}

    def check(self, engine, now):
        s = engine.series["queue_depth"]
        last = s.last()
        if last is not None and last >= self.min_depth \
                and s.increasing_run(self.run):
            return {"value": last, "threshold": self.min_depth,
                    "detail": {"run": self.run}}
        return None


class AcceptanceCollapseDetector(Detector):
    """``specdec_acceptance_rate`` under ``min_rate`` while verify
    ticks MOVED in the window (paying verify forwards for rejected
    drafts).  Knobs: ``DSTPU_ALERT_ACCEPT_MIN`` (0.15),
    ``DSTPU_ALERT_ACCEPT_WINDOW_S`` (60)."""

    name = "acceptance_collapse"
    fire_after = 2
    clear_after = 2

    def __init__(self, min_rate: Optional[float] = None,
                 window_s: Optional[float] = None):
        super().__init__()
        self.min_rate = _envf("DSTPU_ALERT_ACCEPT_MIN", 0.15) \
            if min_rate is None else min_rate
        self.window_s = _envf("DSTPU_ALERT_ACCEPT_WINDOW_S", 60.0) \
            if window_s is None else window_s

    def thresholds(self) -> dict:
        return {"min_rate": self.min_rate, "window_s": self.window_s}

    def check(self, engine, now):
        ticks = engine.series["verify_ticks"].delta(self.window_s, now)
        rate = engine.series["acceptance_rate"].last()
        if ticks and ticks > 0 and rate is not None \
                and rate < self.min_rate:
            return {"value": rate, "threshold": self.min_rate,
                    "detail": {"verify_ticks": ticks}}
        return None


class GoodputDropDetector(Detector):
    """``goodput_ratio`` under ``min_ratio`` once the process has been
    observing for ``min_wall_s`` (warm-up compiles legitimately crater
    the early ratio).  Knobs: ``DSTPU_ALERT_GOODPUT_MIN`` (0.2),
    ``DSTPU_ALERT_GOODPUT_WARMUP_S`` (120)."""

    name = "goodput_drop"
    fire_after = 2
    clear_after = 3

    def __init__(self, min_ratio: Optional[float] = None,
                 min_wall_s: Optional[float] = None):
        super().__init__()
        self.min_ratio = _envf("DSTPU_ALERT_GOODPUT_MIN", 0.2) \
            if min_ratio is None else min_ratio
        self.min_wall_s = _envf("DSTPU_ALERT_GOODPUT_WARMUP_S", 120.0) \
            if min_wall_s is None else min_wall_s

    def thresholds(self) -> dict:
        return {"min_ratio": self.min_ratio, "min_wall_s": self.min_wall_s}

    def check(self, engine, now):
        ratio = engine.series["goodput_ratio"].last()
        wall = engine.series["goodput_wall"].last()
        if ratio is not None and wall is not None \
                and wall >= self.min_wall_s and ratio < self.min_ratio:
            return {"value": ratio, "threshold": self.min_ratio,
                    "detail": {"wall_s": wall}}
        return None


class AttributionDriftDetector(Detector):
    """A measured executable's roofline verdict FLIPPED between
    evaluations (e.g. ``hbm-bound`` → ``overhead-bound``).  Pulse
    semantics: each flip emits exactly one ``firing`` event (with the
    site and both verdicts in ``detail``) and does not stay active —
    drift is an edge, not a state."""

    name = "attribution_drift"

    def __init__(self):
        super().__init__()
        self._last: Dict[str, str] = {}

    def check(self, engine, now):     # unused (step overridden)
        return None

    def step(self, engine, now) -> List[dict]:
        try:
            from . import attribution as _attribution

            verdicts = _attribution.get_plane().verdicts()
        except Exception:
            return []
        events: List[dict] = []
        for site, verdict in verdicts.items():
            prev = self._last.get(site)
            if prev is not None and prev != verdict:
                events.append(self._event("firing", now, {
                    "value": None, "threshold": None,
                    "detail": {"site": site, "from": prev,
                               "to": verdict}}))
            self._last[site] = verdict
        return events


def _finite_median(xs: List[float]) -> Optional[float]:
    import math

    vals = sorted(v for v in xs if math.isfinite(v))
    if not vals:
        return None
    return vals[len(vals) // 2]


class _TrailingRatioDetector(Detector):
    """Shared machinery for the train-series guard rules: the series'
    last sample went non-finite, or rose more than
    ``ratio × max(|baseline|, min_scale)`` ABOVE the baseline (median
    of the trailing ``history`` finite samples, excluding the suspect
    sample itself).  The deviation-from-baseline form stays meaningful
    for negative objectives (ELBO/log-likelihood losses, where a plain
    ``last > ratio·median`` fires on every healthy step) and the
    ``min_scale`` floor keeps a converged near-zero baseline from
    flagging numeric jitter.  The series moves per-step only while a
    ``TrainGuard`` is attached (the per-step device fetch is the
    guard's cost); otherwise it moves at the engine's report cadence
    and the rule stays quiet."""

    fire_after = 2
    clear_after = 3
    series_name = ""            # subclass: which engine.series to read
    env_prefix = ""             # subclass: DSTPU_ALERT_<prefix>_{RATIO,HISTORY}
    default_ratio = 3.0
    min_scale = 1e-3

    def __init__(self, ratio: Optional[float] = None,
                 history: Optional[int] = None):
        super().__init__()
        self.ratio = _envf(f"DSTPU_ALERT_{self.env_prefix}_RATIO",
                           self.default_ratio) if ratio is None else ratio
        self.history = max(4, _envi(f"DSTPU_ALERT_{self.env_prefix}_HISTORY",
                                    8) if history is None else history)

    def thresholds(self) -> dict:
        return {"ratio": self.ratio, "history": self.history}

    def check(self, engine, now):
        import math

        s = engine.series[self.series_name]
        last = s.last()
        if last is None:
            return None
        if not math.isfinite(last):
            return {"value": last, "threshold": None,
                    "detail": {"nonfinite": True}}
        tail = s.tail(self.history + 1)[:-1]      # exclude the suspect
        if len(tail) < self.history // 2:
            return None                            # not enough baseline
        base = _finite_median(tail)
        if base is None:
            return None
        threshold = base + self.ratio * max(abs(base), self.min_scale)
        if last > threshold:
            return {"value": last, "threshold": threshold,
                    "detail": {"median": base, "ratio": self.ratio}}
        return None


class LossSpikeDetector(_TrailingRatioDetector):
    """``train_loss`` non-finite or ``ratio``× above trailing-median.
    Knobs: ``DSTPU_ALERT_LOSS_SPIKE_RATIO`` (3.0),
    ``DSTPU_ALERT_LOSS_SPIKE_HISTORY`` (8, min 4)."""

    name = "loss_spike"
    series_name = "train_loss"
    env_prefix = "LOSS_SPIKE"
    default_ratio = 3.0


class GradNormExplosionDetector(_TrailingRatioDetector):
    """``train_grad_norm`` non-finite or ``ratio``× above
    trailing-median — the fp16 ``overflow``-skip signal generalized:
    under bf16/fp32 nothing else stops a NaN from reaching the
    optimizer.  Knobs: ``DSTPU_ALERT_GRAD_NORM_RATIO`` (10.0),
    ``DSTPU_ALERT_GRAD_NORM_HISTORY`` (8)."""

    name = "grad_norm_explosion"
    series_name = "train_grad_norm"
    env_prefix = "GRAD_NORM"
    default_ratio = 10.0


def default_detectors() -> List[Detector]:
    return [RecompileStormDetector(), SloBurnDetector(),
            QueueRunawayDetector(), AcceptanceCollapseDetector(),
            GoodputDropDetector(), AttributionDriftDetector(),
            LossSpikeDetector(), GradNormExplosionDetector()]


_SOURCES = ("recompiles", "slo_met", "slo_violations", "queue_depth",
            "acceptance_rate", "verify_ticks", "goodput_ratio",
            "goodput_wall", "train_loss", "train_grad_norm")

_MIN_OBSERVE_INTERVAL_S = 1.0
_EVENT_RING = 256


class AnomalyEngine:
    """Samples registry series, runs the detectors, dispatches alert
    events (counters/gauges, ring, subscribers, warning log)."""

    def __init__(self, detectors: Optional[List[Detector]] = None,
                 registry: Optional[_registry.Registry] = None):
        reg = registry or _registry.get_registry()
        self.detectors = default_detectors() if detectors is None \
            else list(detectors)
        self.series: Dict[str, Series] = {n: Series() for n in _SOURCES}
        self.events: deque = deque(maxlen=_EVENT_RING)
        self._active: Dict[str, dict] = {}
        self._subs: List[Callable] = []
        # RLock: the flight recorder's signal handler reads status()
        # from the main thread, possibly mid-observe
        self._lock = threading.RLock()
        self._last_obs = 0.0
        self._m_alerts = reg.counter(
            "alerts_total", "structured alert firings", labelnames=("rule",))
        self._m_firing = reg.gauge(
            "alerts_firing", "1 while the rule's alert is active",
            labelnames=("rule",))

    # -- sampling -------------------------------------------------------
    def _sample(self, now: float) -> None:
        from . import recompile as _recompile

        def put(name: str, v: Optional[float]) -> None:
            if v is not None:
                self.series[name].add(now, v)

        put("recompiles", _recompile.total_recompiles())
        put("slo_met", _metric_total("serving_slo_met_total"))
        put("slo_violations", _metric_total("serving_slo_violations_total"))
        put("queue_depth", _metric_total("serving_queue_depth"))
        put("acceptance_rate", _metric_total("specdec_acceptance_rate"))
        put("verify_ticks", _metric_total("specdec_verify_ticks_total"))
        put("train_loss", _metric_total("train_loss"))
        put("train_grad_norm", _metric_total("train_grad_norm"))
        try:
            from . import goodput as _goodput

            tracker = _goodput.get_tracker()
            with tracker._lock:
                t0 = tracker._t0
                compute = tracker._totals.get("compute", 0.0)
            if t0 is not None:
                wall = max(time.monotonic() - t0, 1e-9)
                put("goodput_ratio", min(1.0, compute / wall))
                put("goodput_wall", wall)
        except Exception:
            pass

    # -- evaluation -----------------------------------------------------
    def observe(self, now: Optional[float] = None,
                force: bool = False) -> List[dict]:
        """Sample + evaluate (throttled to ~1/s unless ``force``);
        returns the transition events this evaluation produced.

        The engine lock covers ONLY sampling, detector evaluation, and
        the ring/active-set updates; metrics, the warning log, and the
        subscriber fan-out run after it is released.  A slow subscriber
        (the admission-controller seam) must never hold the lock the
        flight recorder's signal-handler dump path (``status()``) needs
        from another thread — that would hang the crash forensics."""
        with self._lock:
            mono = time.monotonic()
            if not force and mono - self._last_obs < _MIN_OBSERVE_INTERVAL_S:
                return []
            self._last_obs = mono
            now = time.time() if now is None else now
            self._sample(now)
            events: List[dict] = []
            for d in self.detectors:
                try:
                    events.extend(d.step(self, now))
                except Exception as e:     # one broken detector ≠ no alerts
                    logger.debug(f"anomaly: detector {d.name} failed: {e!r}")
            for ev in events:
                self._record(ev)
        for ev in events:
            self._emit(ev)
        return events

    def _record(self, ev: dict) -> None:
        """State mutation only (caller holds the lock): the event ring
        and the active set.  The active set is keyed by ``ev["key"]``
        when present (external emitters like the fleet aggregator track
        one alert PER REPLICA under one rule name) and by the rule
        otherwise."""
        self.events.append(ev)
        # pulse rules (attribution drift) never stay active
        pulse = any(d.name == ev["rule"]
                    and isinstance(d, AttributionDriftDetector)
                    for d in self.detectors)
        key = ev.get("key", ev["rule"])
        if ev["state"] == "firing" and not pulse:
            self._active[key] = ev
        else:
            self._active.pop(key, None)

    def _emit(self, ev: dict) -> None:
        """Side effects OUTSIDE the engine lock: registry metrics (own
        lock), warning log, subscriber callbacks."""
        # with keyed (per-replica) alerts, the rule's firing gauge stays
        # 1 until the LAST active key under that rule clears
        rule_firing = any(e["rule"] == ev["rule"]
                          for e in self.active().values())
        if ev["state"] == "firing":
            self._m_alerts.labels(rule=ev["rule"]).inc()
            self._m_firing.labels(rule=ev["rule"]).set(
                1.0 if rule_firing else 0.0)
            logger.warning(
                f"ALERT {ev['rule']} firing: value={ev['value']} "
                f"threshold={ev['threshold']} detail={ev['detail']}")
        else:
            self._m_firing.labels(rule=ev["rule"]).set(
                1.0 if rule_firing else 0.0)
            logger.warning(f"ALERT {ev['rule']} cleared")
        for fn in list(self._subs):
            try:
                fn(ev)
            except Exception:
                pass          # a subscriber must never break telemetry

    def emit_event(self, rule: str, state: str, *, value=None,
                   threshold=None, detail: Optional[dict] = None,
                   key: Optional[str] = None,
                   now: Optional[float] = None) -> dict:
        """Record + dispatch an externally-produced alert transition —
        the seam for state machines that live OUTSIDE the detector loop
        (the fleet aggregator's replica health transitions).  The event
        rides the exact machinery detector transitions do:
        ``alerts_total{rule}`` / ``alerts_firing{rule}``, the event ring
        + ``/alertz`` active set (keyed by ``key`` so one rule can track
        N replicas), the warning log, and every subscriber."""
        ev = {"rule": rule, "state": state,
              "t": time.time() if now is None else now,
              "value": value, "threshold": threshold,
              "detail": detail or {}}
        if key is not None:
            ev["key"] = key
        with self._lock:
            self._record(ev)
        self._emit(ev)
        return ev

    def reset_rules(self, names, series=()) -> None:
        """Quiesce the named rules (and optionally clear source series)
        WITHOUT emitting clear transitions: after a TrainGuard rollback
        the pre-rollback samples say nothing about the restored state,
        and a synthetic "cleared" event would unwind subscribers that
        never saw the firing resolve for real."""
        wanted = set(names)
        with self._lock:
            for d in self.detectors:
                if d.name in wanted:
                    d.reset()
            for key in [k for k, ev in self._active.items()
                        if ev["rule"] in wanted]:
                self._active.pop(key, None)
            for s in series:
                if s in self.series:
                    self.series[s].clear()
        for name in wanted:
            self._m_firing.labels(rule=name).set(0.0)

    # -- the consumer seam ---------------------------------------------
    def subscribe(self, fn: Callable[[dict], None]) -> Callable[[], None]:
        """Register ``fn(event)`` for every alert transition — the seam
        the admission controller / load shedder consumes.  Returns a
        zero-arg remover."""
        self._subs.append(fn)

        def remove():
            if fn in self._subs:
                self._subs.remove(fn)
        return remove

    # -- export ---------------------------------------------------------
    def active(self) -> Dict[str, dict]:
        with self._lock:
            return dict(self._active)

    def recent(self, n: int = 20) -> List[dict]:
        with self._lock:
            return list(self.events)[-n:]

    def status(self) -> dict:
        """The ``/alertz`` payload (also the ``/statusz`` ``alerts``
        section and the flight dump's ``alerts`` entry)."""
        with self._lock:
            return {
                "active": list(self._active.values()),
                "recent": list(self.events)[-20:],
                "rules": {d.name: {"firing": d.firing,
                                   **d.thresholds()}
                          for d in self.detectors},
            }


_default: Optional[AnomalyEngine] = None


def get_engine() -> AnomalyEngine:
    global _default
    if _default is None:
        _default = AnomalyEngine()
    return _default


def observe(now: Optional[float] = None, force: bool = False) -> List[dict]:
    return get_engine().observe(now=now, force=force)


def subscribe(fn: Callable[[dict], None]) -> Callable[[], None]:
    return get_engine().subscribe(fn)


def active() -> Dict[str, dict]:
    return get_engine().active()


def recent(n: int = 20) -> List[dict]:
    return get_engine().recent(n)


def status() -> dict:
    return get_engine().status()


_installed = False


def install() -> AnomalyEngine:
    """Arm the default engine: evaluate on every scrape (collector) and
    publish the ``/statusz`` ``alerts`` section.  Idempotent; called on
    telemetry import.  Per-step evaluation additionally rides
    ``goodput.note_step`` (throttled inside :meth:`observe`)."""
    global _installed
    eng = get_engine()
    if not _installed:
        from . import exporter as _exporter

        # resolve the singleton at CALL time (tests swap it)
        _registry.register_collector(lambda: get_engine().observe())
        _exporter.register_status_provider(
            "alerts", lambda: get_engine().status())
        _installed = True
    return eng
