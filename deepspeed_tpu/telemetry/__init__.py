"""Unified telemetry layer: metrics registry + step tracer + recompile
watchdog.

Three coordinated surfaces replacing the reference's scattered
``monitor/`` / ``utils/timer.py`` / profiler observability:

- :mod:`.registry` — process-wide counters/gauges/histograms with JSON
  (``snapshot()``) and Prometheus-text export; every subsystem
  (``MonitorMaster`` events, ``ThroughputTimer``, serving latency,
  heartbeats, the watchdog) publishes here.
- :mod:`.trace` — host-side span tracing emitting Chrome-trace JSON
  (Perfetto-viewable), wired into the train-engine phases, the serving
  loop, and (via ``device_span``/HLO metadata) pipeline stage bodies.
- :mod:`.recompile` — watchdog over jitted hot loops that counts
  distinct compile signatures and warns when a warm loop recompiles.

Launcher integration: ``dstpu --metrics_dir DIR`` injects
``DSTPU_METRICS_DIR`` so every rank dumps ``metrics_rank<k>.json`` on
exit; ``DSTPU_TRACE=/path.json`` auto-enables tracing and writes the
trace on exit (use ``{rank}`` in the path for multi-rank runs).
"""
from . import recompile, trace  # noqa: F401
from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, counter, gauge, get_registry,
    histogram, maybe_install_exit_dump,
)

# arm the per-rank exit dump when the launcher asked for one
maybe_install_exit_dump()
