"""Unified telemetry layer: metrics registry + step tracer + recompile
watchdog + live observability plane.

Seven coordinated surfaces replacing the reference's scattered
``monitor/`` / ``utils/timer.py`` / profiler observability:

- :mod:`.registry` — process-wide counters/gauges/histograms with JSON
  (``snapshot()``) and Prometheus-text export; every subsystem
  (``MonitorMaster`` events, ``ThroughputTimer``, serving latency,
  heartbeats, the watchdog) publishes here.
- :mod:`.trace` — host-side span tracing emitting Chrome-trace JSON
  (Perfetto-viewable), wired into the train-engine phases, the serving
  loop, and (via ``device_span``/HLO metadata) pipeline stage bodies.
- :mod:`.recompile` — watchdog over jitted hot loops that counts
  distinct compile signatures and warns when a warm loop recompiles.
- :mod:`.exporter` — per-rank HTTP server (``/metrics`` Prometheus
  text, ``/healthz`` liveness JSON, ``/statusz`` operational JSON);
  opt-in via ``dstpu --telemetry_port`` / ``DSTPU_TELEMETRY_PORT``.
- :mod:`.goodput` — step-phase wall-time attribution (compute /
  data-wait / checkpoint / recompile / idle) + ``goodput_ratio``.
- :mod:`.memory` — per-executable HBM accounting
  (``compiled.memory_analysis()`` normalized behind ONE helper) and
  live-array memory gauges sampled at scrape time.
- :mod:`.flightrec` — always-on crash flight recorder (last spans /
  logs / metric deltas) dumped on atexit, SIGTERM/SIGABRT, and
  unhandled exceptions; the launcher pretty-prints it on restart.
- :mod:`.fleet` — the multi-replica rollup: scrapes N per-rank
  exporters (static list / env / the launcher-written ``fleet.json``),
  merges them per metric kind, runs a per-replica health state
  machine, and serves ``/fleetz`` + a federated ``/metrics`` — the
  ``FleetView`` seam the multi-replica router steers by.
- :mod:`.reqtrace` — request-scoped distributed tracing: per-request
  span trees off the serving lifecycle observers (``traceparent``
  propagation, deterministic trace ids), tail-based retention (head
  sampling plus unconditional promotion of SLO-violating and
  alert-coincident requests), ``/tracez``, Perfetto export on the
  ``trace.py`` time axis, and the fleet stitcher.  Opt-in via
  ``DSTPU_REQTRACE=1``.

Launcher integration: ``dstpu --metrics_dir DIR`` injects
``DSTPU_METRICS_DIR`` so every rank dumps ``metrics_rank<k>.json`` on
exit (and, with the flight recorder, on SIGTERM) plus
``flight_<k>.json`` forensics; ``dstpu --telemetry_port P`` serves the
live endpoints on ``P + rank``; ``DSTPU_TRACE=/path.json`` auto-enables
tracing and writes the trace on exit (use ``{rank}`` in the path for
multi-rank runs).
"""
from . import recompile, trace  # noqa: F401
from .registry import (  # noqa: F401
    Counter, Gauge, Histogram, Registry, counter, gauge, get_registry,
    histogram, maybe_install_exit_dump,
)
from . import goodput, memory  # noqa: F401  (need registry+trace above)
from . import exporter, flightrec  # noqa: F401
from . import anomaly, attribution  # noqa: F401  (need exporter above)
from . import fleet  # noqa: F401  (needs registry + anomaly above)
from . import reqtrace  # noqa: F401  (needs registry + trace above)

# arm the per-rank exit dump when the launcher asked for one
maybe_install_exit_dump()
# goodput attribution rides span boundaries; always on (near-free)
goodput.install()
# live-HBM gauges refresh on every scrape/dump
from .registry import register_collector as _register_collector  # noqa: E402

_register_collector(memory.sample_live_hbm)
# roofline attribution (/profilez, opt-in sampling via
# DSTPU_ATTRIBUTION) + anomaly/alert detectors (/alertz, evaluated on
# scrapes and step boundaries)
attribution.install()
anomaly.install()
# crash forensics when a dump dir is configured; live endpoints when a
# port is configured
flightrec.maybe_install()
exporter.maybe_start()
