"""Goodput attribution: classify step wall time into phases.

A train/serving tick's wall time is one undifferentiated number in the
throughput log; operations wants to know WHERE it went — is the job
compute-bound (good), input-bound (fix the loader), stuck compiling
(fix the shape drift), or blocked writing checkpoints?  This module
splits the host wall clock into phases:

- ``compute``    — fwd/bwd dispatch + decode ticks (the useful work),
- ``data_wait``  — batch load + host→device put, serving admission,
- ``checkpoint`` — save/restore wall time,
- ``recompile``  — jit trace+compile time (warm-up AND drift; reported
  by the recompilation watchdog),
- ``idle``       — wall time covered by none of the above (derived).

Attribution rides the tracer's span boundaries (:mod:`.trace` notifies a
span observer whether or not Chrome-trace recording is on), so the
engine/serving loops need no extra instrumentation, and it is
EXCLUSIVE: a ``train/checkpoint`` span nested inside a ``train/fwd-bwd``
span bills the checkpoint seconds to ``checkpoint`` only, and compile
seconds reported mid-span are subtracted from the enclosing phase.

Export surface (the metrics registry): per-phase time histograms
(``goodput_phase_seconds{phase=...}``), cumulative per-phase totals
(``goodput_phase_seconds_total``), and — refreshed by a registered
collector on every scrape — ``goodput_ratio`` (compute / total wall
since the first observation) plus ``goodput_idle_seconds_total``.
"""
from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from . import registry as _registry

__all__ = ["GoodputTracker", "get_tracker", "install", "phase",
           "note_compile", "note_step", "last_step_age", "summary",
           "PHASES", "SPAN_PHASE"]

PHASES = ("compute", "data_wait", "checkpoint", "recompile")

# span name -> phase.  Admission is host-side scheduling/queueing work
# (the serving analog of waiting on input); prefill/decode are the
# useful serving compute.
SPAN_PHASE = {
    "train/fwd-bwd": "compute",
    "train/apply-step": "compute",
    "train/load-batch": "data_wait",
    "train/checkpoint": "checkpoint",
    "serve/prefill": "compute",
    "serve/decode-tick": "compute",
    "serve/verify-tick": "compute",   # speculative batched verify forward
    "serve/admission": "data_wait",
}

_tls = threading.local()


def _stack():
    s = getattr(_tls, "stack", None)
    if s is None:
        s = _tls.stack = []
    return s


class GoodputTracker:
    """Span observer + manual ``phase(...)`` API accumulating per-phase
    wall seconds; registered into :mod:`.trace` by :func:`install`."""

    def __init__(self, registry: Optional[_registry.Registry] = None,
                 span_phase: Optional[dict] = None):
        reg = registry or _registry.get_registry()
        self._span_phase = dict(SPAN_PHASE if span_phase is None
                                else span_phase)
        # RLock: the flight-recorder signal handler reads summary() from
        # the main thread, possibly interrupting note_step mid-hold
        self._lock = threading.RLock()
        self._totals: Dict[str, float] = {p: 0.0 for p in PHASES}
        self._t0: Optional[float] = None       # first observation (mono)
        self._last_step_mono: Optional[float] = None
        self._last_step_wall: Optional[float] = None
        self._steps_by_kind: Dict[str, int] = {}
        self._h = reg.histogram(
            "goodput_phase_seconds",
            "per-occurrence wall time by phase (exclusive attribution)",
            labelnames=("phase",), buckets=_registry.SECONDS_BUCKETS)
        self._c = reg.counter(
            "goodput_phase_seconds_total",
            "cumulative wall seconds by phase", labelnames=("phase",))
        self._ratio = reg.gauge(
            "goodput_ratio",
            "compute seconds / total wall seconds since first observation")
        self._idle = reg.gauge(
            "goodput_idle_seconds_total",
            "wall seconds attributed to no phase since first observation")
        self._wall = reg.gauge(
            "goodput_wall_seconds_total",
            "wall seconds since the first observed phase")

    # -- span observer protocol (see trace.add_span_observer) ----------
    def span_enter(self, name: str) -> None:
        _stack().append(0.0)    # seconds already billed by nested phases

    def span_exit(self, name: str, dur_s: float, args) -> None:
        stack = _stack()
        billed_children = stack.pop() if stack else 0.0
        ph = self._span_phase.get(name)
        if ph is not None:
            self._observe(ph, max(0.0, dur_s - billed_children))
            claimed = dur_s          # whole interval now accounted for
        else:
            claimed = billed_children   # propagate nested claims upward
        if stack:
            stack[-1] += claimed

    # -- accumulation ---------------------------------------------------
    def _observe(self, ph: str, dur_s: float) -> None:
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic() - dur_s
            self._totals[ph] = self._totals.get(ph, 0.0) + dur_s
        self._h.labels(phase=ph).observe(dur_s)
        self._c.labels(phase=ph).inc(dur_s)

    def note_compile(self, dur_s: float) -> None:
        """Bill ``dur_s`` of jit trace+compile time to ``recompile`` and
        subtract it from the enclosing span's phase (the compile happens
        INSIDE e.g. a ``train/fwd-bwd`` interval)."""
        self._observe("recompile", dur_s)
        stack = _stack()
        if stack:
            stack[-1] += dur_s

    def phase(self, name: str):
        """Manual attribution context for code outside the pre-wired
        spans: ``with goodput.phase("compute"): ...``."""
        from . import trace as _trace

        if name not in PHASES:
            raise ValueError(f"unknown phase {name!r}; one of {PHASES}")
        self._span_phase.setdefault(f"goodput/{name}", name)
        return _trace.span(f"goodput/{name}")

    def note_step(self, kind: str = "train",
                  context: Optional[dict] = None) -> None:
        """Record that a step/tick completed — powers the ``/healthz``
        last-step-age check and the flight recorder's metric-delta marks.
        ``context`` (small JSON-ables, e.g. serving's in-flight request
        uids) rides the flight-recorder delta entry for postmortems."""
        with self._lock:
            self._last_step_mono = time.monotonic()
            self._last_step_wall = time.time()
            self._steps_by_kind[kind] = self._steps_by_kind.get(kind, 0) + 1
        try:
            from . import flightrec

            flightrec.mark(kind, context)
        except Exception:
            pass
        try:
            # anomaly detectors evaluate at step cadence (throttled to
            # ~1/s inside observe) — no extra thread, no extra sync
            from . import anomaly

            anomaly.observe()
        except Exception:
            pass

    def last_step_age(self) -> Optional[float]:
        """Seconds since the last completed step, None before the first."""
        with self._lock:
            if self._last_step_mono is None:
                return None
            return time.monotonic() - self._last_step_mono

    # -- export ---------------------------------------------------------
    def refresh_gauges(self) -> None:
        """Recompute ratio/idle/wall gauges (collector; runs per scrape)."""
        with self._lock:
            if self._t0 is None:
                return
            total = max(time.monotonic() - self._t0, 1e-9)
            tracked = sum(self._totals.values())
            compute = self._totals.get("compute", 0.0)
        self._wall.set(total)
        self._idle.set(max(0.0, total - tracked))
        self._ratio.set(min(1.0, compute / total))

    def summary(self) -> dict:
        """Phase breakdown + ratio as a JSON-able dict (statusz/probe)."""
        self.refresh_gauges()
        with self._lock:
            out = {f"{p}_s": round(self._totals.get(p, 0.0), 6)
                   for p in PHASES}
            t0 = self._t0
            total = (time.monotonic() - t0) if t0 is not None else 0.0
            out["steps"] = dict(self._steps_by_kind)
        out["wall_s"] = round(total, 6)
        out["idle_s"] = round(max(0.0, total - sum(
            out[f"{p}_s"] for p in PHASES)), 6)
        out["goodput_ratio"] = (
            min(1.0, out["compute_s"] / total) if total > 0 else None)
        age = self.last_step_age()
        out["last_step_age_s"] = None if age is None else round(age, 3)
        return out


_default: Optional[GoodputTracker] = None


def get_tracker() -> GoodputTracker:
    global _default
    if _default is None:
        _default = GoodputTracker()
    return _default


_installed = False


def install() -> GoodputTracker:
    """Arm the default tracker: subscribe to span boundaries and register
    the ratio-refresh collector.  Idempotent; called on telemetry import."""
    global _installed
    t = get_tracker()
    if not _installed:
        from . import trace as _trace

        _trace.add_span_observer(t)
        _registry.register_collector(t.refresh_gauges)
        _installed = True
    return t


# module-level conveniences over the default tracker
def phase(name: str):
    return get_tracker().phase(name)


def note_compile(dur_s: float) -> None:
    get_tracker().note_compile(dur_s)


def note_step(kind: str = "train", context: Optional[dict] = None) -> None:
    get_tracker().note_step(kind, context)


def last_step_age() -> Optional[float]:
    return get_tracker().last_step_age()


def summary() -> dict:
    return get_tracker().summary()
