"""Per-executable roofline attribution: device-time verdicts.

The stack can *measure* (registry counters, spans, goodput, the load
harness) but until now could not *attribute*: ``bench.py``'s
``bw_floor_frac`` was one hand-derived number for one executable, and
the W8A16 regression sat unexplained for six bench rounds until a
manual profile traced it to launch-count asymmetry.  This module makes
that attribution automatic, for every hot executable:

- **Costs** — ``compiled.cost_analysis()`` FLOPs / bytes-accessed are
  harvested wherever a ``Compiled`` handle already exists
  (:func:`telemetry.memory.record_compiled` forwards every AOT site:
  ``engine.record_memory_profile``, serving ``warmup_windows`` /
  ``_warmup_admission`` incl. place/retire, the flops profiler), plus a
  lazy one-shot ``lower().compile()`` harvest (:meth:`ensure_costs`)
  for executables that only materialize inside the hot loop (specdec
  verify widths, prefill chunks).
- **Measured time** — sampled timing windows: 1-in-N ticks/steps
  (``DSTPU_ATTRIBUTION_SAMPLE``, default 8) record host wall time for
  the executable, behind the opt-in ``DSTPU_ATTRIBUTION=1`` flag.
  Serving windows are already fenced by their token fetch, so sampling
  there costs a dict update; the train step and prefill chunks fence
  via ``block_until_ready`` only on sampled iterations.
- **Verdicts** — each (costs, timing) pair yields ``mfu`` (flops vs the
  chip's peak), ``bw_frac`` (bytes vs the chip's HBM bandwidth), and a
  bound-class verdict: ``compute-bound`` / ``hbm-bound`` /
  ``overhead-bound`` (neither roof within reach — dispatch/launch
  overhead dominates, the W8A16 failure class).

Export surfaces: ``/profilez`` (the full per-executable table), the
``/statusz`` ``attribution`` section, ``attribution_*`` registry
gauges, and the flight dump (a crash postmortem shows what was slow).
This module also owns THE device physics tables (peak FLOPs, HBM
bytes/s) — ``bench.py`` and ``profiling/flops_profiler.py`` read them
from here, so the bench and the live plane can never report different
physics for the same executable.
"""
from __future__ import annotations

import os
import statistics
import sys
import threading
import time
from collections import deque
from typing import Optional

from ..utils.logging import logger
from . import registry as _registry

__all__ = [
    "ATTRIBUTION_ENV", "SAMPLE_ENV", "PEAK_FLOPS", "HBM_BYTES_S",
    "device_peak_flops", "device_hbm_bytes_s", "harvest_costs",
    "roofline", "decode_stream_floor", "AttributionPlane", "get_plane",
    "enabled", "enable", "should_sample", "note_compiled", "note_measured",
    "note_window", "ensure_costs", "timed_jit_call", "snapshot", "status",
    "install", "capture_trace",
]

ATTRIBUTION_ENV = "DSTPU_ATTRIBUTION"
SAMPLE_ENV = "DSTPU_ATTRIBUTION_SAMPLE"

# -- device physics (THE one copy; bench.py + flops_profiler read these)
# bf16 peak FLOPs per chip by TPU generation; "cpu" is a nominal 1 TF so
# CPU-mesh runs still produce finite (tiny) MFUs instead of NaNs.
PEAK_FLOPS = {"v4": 275e12, "v5 lite": 197e12, "v5e": 197e12,
              "v5p": 459e12, "v6 lite": 918e12, "v6e": 918e12,
              "cpu": 1e12}

# HBM bandwidth per chip (bytes/s) — the decode bandwidth-floor
# denominator: a decode tick streams every weight byte plus the live KV
# cache, so floor_ms = bytes / BW is the physics bound serving numbers
# are judged against.
HBM_BYTES_S = {"v4": 1228e9, "v5 lite": 819e9, "v5e": 819e9,
               "v5p": 2765e9, "v6 lite": 1640e9, "v6e": 1640e9,
               "cpu": 50e9}

# verdict threshold: a roof (mfu or bw_frac) must explain at least this
# fraction of the measured time to call the executable bound by it;
# below both roofs the time is going to dispatch/launch overhead.
_OVERHEAD_FRAC_ENV = "DSTPU_ATTRIBUTION_OVERHEAD_FRAC"
_DEFAULT_OVERHEAD_FRAC = 0.10

_SAMPLE_WINDOW = 32        # timing samples retained per site (median)


def _device_lookup(dev, table: dict, default: Optional[float]
                   ) -> Optional[float]:
    kind = getattr(dev, "device_kind", "").lower() if dev is not None else ""
    for key, val in table.items():
        if key in kind:
            return val
    return default


def device_peak_flops(dev=None, default: Optional[float] = 1e12
                      ) -> Optional[float]:
    """Peak bf16 FLOPs/s of ``dev`` (device 0 when None) from
    :data:`PEAK_FLOPS`; ``default`` for unknown kinds."""
    if dev is None:
        dev = _device0()
    return _device_lookup(dev, PEAK_FLOPS, default)


def device_hbm_bytes_s(dev=None, default: Optional[float] = 50e9
                       ) -> Optional[float]:
    """HBM bandwidth (bytes/s) of ``dev`` from :data:`HBM_BYTES_S`."""
    if dev is None:
        dev = _device0()
    return _device_lookup(dev, HBM_BYTES_S, default)


def _device0():
    """Local device 0 WITHOUT forcing a jax import/backend init (this
    module is imported at ``import deepspeed_tpu`` time)."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    try:
        return jax.local_devices()[0]
    except Exception:
        return None


def harvest_costs(compiled) -> Optional[dict]:
    """THE ``cost_analysis()`` normalizer: ``{"flops", "bytes_accessed",
    "transcendentals"}`` (floats) or None when the backend exposes no
    analysis.  ``profiling/flops_profiler.py`` delegates here — the
    profiler, the bench, and the live plane share one reading of the
    compiler's numbers."""
    try:
        costs = compiled.cost_analysis()
    except Exception:
        return None
    if isinstance(costs, (list, tuple)):     # some backends: [dict]
        costs = costs[0] if costs else None
    if costs is None:
        return None
    costs = dict(costs)
    return {
        "flops": float(costs.get("flops", 0.0)),
        "bytes_accessed": float(costs.get("bytes accessed", 0.0)),
        "transcendentals": float(costs.get("transcendentals", 0.0)),
    }


def roofline(flops: float, hbm_bytes: float, seconds: float,
             peak_flops: float, hbm_bytes_s: float,
             overhead_frac: Optional[float] = None) -> dict:
    """Roofline verdict for one executable invocation.

    ``mfu`` = achieved FLOPs/s over peak; ``bw_frac`` = achieved
    bytes/s over HBM bandwidth.  The verdict names the roof the
    measured time is actually pressed against:

    - ``compute-bound`` — mfu is the binding (larger) fraction;
    - ``hbm-bound``     — bw_frac is the binding fraction;
    - ``overhead-bound`` — NEITHER roof explains ``overhead_frac`` of
      the time: the executable is dominated by dispatch/launch/host
      overhead (the W8A16 launch-asymmetry class), and streaming fewer
      bytes or doing fewer FLOPs will not make it faster.
    """
    if overhead_frac is None:
        try:
            overhead_frac = float(os.environ.get(
                _OVERHEAD_FRAC_ENV, _DEFAULT_OVERHEAD_FRAC))
        except ValueError:
            overhead_frac = _DEFAULT_OVERHEAD_FRAC
    seconds = max(float(seconds), 1e-12)
    mfu = flops / (seconds * peak_flops) if peak_flops else 0.0
    bw_frac = hbm_bytes / (seconds * hbm_bytes_s) if hbm_bytes_s else 0.0
    if max(mfu, bw_frac) < overhead_frac:
        verdict = "overhead-bound"
    elif bw_frac >= mfu:
        verdict = "hbm-bound"
    else:
        verdict = "compute-bound"
    return {"mfu": mfu, "bw_frac": bw_frac, "verdict": verdict}


def decode_stream_floor(params, slot_cache, n_slots: int, dev=None) -> dict:
    """The decode-tick HBM bandwidth floor: every stored weight byte
    plus the slots' KV caches must stream from HBM each tick, so
    ``bw_floor_ms_per_tick`` is the physics bound a measured
    ms-per-tick is judged against.  ``slot_cache`` is a ONE-slot cache
    tree (arrays or ``ShapeDtypeStruct``\\ s — ``eval_shape`` is fine).
    This is ``bench.py --mode serving``'s accounting, shared so the
    bench and the live plane cannot disagree on the same executable's
    physics."""
    from . import memory as _memory

    weight_bytes = _memory.tree_bytes(params)
    kv_bytes = int(n_slots) * _memory.tree_bytes(slot_cache)
    bw = device_hbm_bytes_s(dev)
    return {
        "weight_stream_bytes": int(weight_bytes),
        "kv_stream_bytes_per_tick": int(kv_bytes),
        "hbm_bytes_s": float(bw),
        "bw_floor_ms_per_tick": 1000.0 * (weight_bytes + kv_bytes) / bw,
    }


class AttributionPlane:
    """Process-wide per-executable (site) cost + timing store.

    Sites are the recompile-watchdog names (``serving.decode[16g]``,
    ``serving.verify[4g]``, ``engine.train_step`` …), so every surface
    — watchdog warnings, HBM gauges, this table — speaks one naming
    scheme."""

    def __init__(self, registry: Optional[_registry.Registry] = None):
        reg = registry or _registry.get_registry()
        # RLock: the flight recorder's signal handler snapshots from the
        # main thread, possibly interrupting note_measured mid-hold
        self._lock = threading.RLock()
        self._sites: dict = {}
        self._tick_counts: dict = {}
        self._cost_failed: set = set()
        self._first_skipped: set = set()
        self._forced: Optional[bool] = None
        self._physics: Optional[tuple] = None    # (kind, peak, bw)
        self._m_samples = reg.counter(
            "attribution_samples_total",
            "timed executable windows recorded", labelnames=("site",))
        self._m_ms = reg.gauge(
            "attribution_measured_ms",
            "median sampled wall ms of the executable",
            labelnames=("site",))
        self._m_mfu = reg.gauge(
            "attribution_mfu",
            "achieved FLOPs/s over device peak", labelnames=("site",))
        self._m_bw = reg.gauge(
            "attribution_bw_frac",
            "achieved bytes/s over device HBM bandwidth",
            labelnames=("site",))

    # -- enablement ----------------------------------------------------
    def enabled(self) -> bool:
        if self._forced is not None:
            return self._forced
        return os.environ.get(ATTRIBUTION_ENV, "") not in ("", "0")

    def enable(self, on: Optional[bool] = True) -> None:
        """Programmatic override of ``DSTPU_ATTRIBUTION`` (None defers
        back to the env)."""
        self._forced = on

    def sample_every(self) -> int:
        try:
            return max(1, int(os.environ.get(SAMPLE_ENV, "8")))
        except ValueError:
            return 8

    def should_sample(self, site: str) -> bool:
        """1-in-N per-site sampling decision; the FIRST call per site
        always samples (deterministic warm coverage)."""
        with self._lock:
            n = self._tick_counts.get(site, 0)
            self._tick_counts[site] = n + 1
        return n % self.sample_every() == 0

    # -- costs ---------------------------------------------------------
    def _site(self, site: str) -> dict:
        with self._lock:
            s = self._sites.get(site)
            if s is None:
                s = self._sites[site] = {
                    # dstpu-lint: disable-next-line=DSTPU006 -- hbm_bytes is the /profilez row KEY (the ISSUE-specified field name), not a registry metric
                    "flops": None, "hbm_bytes": None, "costs_src": None,
                    "samples": deque(maxlen=_SAMPLE_WINDOW), "calls": 0}
            return s

    def note_costs(self, site: str, flops: float, hbm_bytes: float,
                   src: str = "aot") -> None:
        s = self._site(site)
        with self._lock:
            s["flops"] = float(flops)
            # dstpu-lint: disable-next-line=DSTPU006 -- hbm_bytes is the /profilez row KEY, not a registry metric
            s["hbm_bytes"] = float(hbm_bytes)
            s["costs_src"] = src

    def note_compiled(self, compiled, site: str, src: str = "aot"
                      ) -> Optional[dict]:
        """Harvest ``compiled.cost_analysis()`` into the site (no-op
        when the backend exposes none).  Called by
        ``telemetry.memory.record_compiled`` at every AOT point, so
        existing compile sites feed the table for free."""
        costs = harvest_costs(compiled)
        if costs is None:
            return None
        self.note_costs(site, costs["flops"], costs["bytes_accessed"],
                        src=src)
        return costs

    def ensure_costs(self, site: str, jitfn, *args) -> None:
        """One-shot lazy cost harvest for executables with no AOT
        compile point: ``jitfn.lower(*args).compile()`` (abstract — no
        execution, donation-safe) then harvest.  A site that ever fails
        is never retried (one warning, not a per-window stall)."""
        s = self._site(site)
        with self._lock:
            if s["flops"] is not None or site in self._cost_failed:
                return
            self._cost_failed.add(site)    # claim: only one attempt ever
        try:
            compiled = jitfn.lower(*args).compile()
        except Exception as e:
            logger.debug(f"attribution: cost harvest failed for "
                         f"{site!r}: {e!r}")
            return
        if self.note_compiled(compiled, site, src="lazy") is None:
            logger.debug(f"attribution: no cost_analysis for {site!r}")

    # -- measured time -------------------------------------------------
    def note_measured(self, site: str, wall_s: float, calls: int = 1
                      ) -> None:
        """Record one sampled timing window for ``site`` (host wall
        seconds per executable invocation) and refresh the site's
        gauges/verdict."""
        ms = 1000.0 * float(wall_s) / max(1, int(calls))
        s = self._site(site)
        with self._lock:
            s["samples"].append(ms)
            s["calls"] += int(calls)
        self._m_samples.labels(site=site).inc(calls)
        row = self._row(site, s)
        self._m_ms.labels(site=site).set(row["measured_ms"])
        if row["mfu"] is not None:
            self._m_mfu.labels(site=site).set(row["mfu"])
            self._m_bw.labels(site=site).set(row["bw_frac"])

    def _should_record(self, site: str, jitfn, sigs_before) -> bool:
        """Was this window a STEADY-STATE execution (no trace+compile
        inside the call)?  Compile wall is not device time, and one
        warm-up outlier would poison a once-only site's verdict.

        Primary signal: the recompile watchdog's ``signatures_seen``
        (unchanged across the call ⇒ no compile).  When the watchdog is
        disabled the wrapper — and the signal — is absent; falling back
        to "record everything" would record exactly the first sampled
        call, which is the one that pays the full XLA compile.  So with
        no signature visibility the FIRST sampled call per site is
        skipped and later ones recorded (post-warm-up compiles are the
        rare case the watchdog exists to catch anyway)."""
        if sigs_before is not None:
            return getattr(jitfn, "signatures_seen", None) == sigs_before
        with self._lock:
            if site in self._first_skipped:
                return True
            self._first_skipped.add(site)
            return False

    def note_window(self, site: str, wall_s: float, jitfn=None,
                    sigs_before=None, args: tuple = ()) -> bool:
        """Record one already-measured window for ``site`` if it was
        steady-state (see :meth:`_should_record`); on a recorded window
        with ``args``, ALSO run the one-shot lazy cost harvest — AFTER
        the timed interval, on a warm executable, so the harvest's
        ``lower().compile()`` never lands inside a measurement and
        never doubles a cold compile.  ``lower`` only reads avals, so
        donated/deleted buffers in ``args`` are safe.  Returns whether
        the sample was recorded."""
        if not self._should_record(site, jitfn, sigs_before):
            return False
        self.note_measured(site, wall_s)
        if jitfn is not None and args:
            self.ensure_costs(site, jitfn, *args)
        return True

    def timed_jit_call(self, site: str, jitfn, *args):
        """Call ``jitfn(*args)``; on sampled iterations, fence the
        result (``block_until_ready``), record the wall time, and —
        only once the site runs steady — harvest its costs.  The
        non-sampled path is one counter increment."""
        if not self.should_sample(site):
            return jitfn(*args)
        import jax

        sigs0 = getattr(jitfn, "signatures_seen", None)
        t0 = time.perf_counter()
        out = jitfn(*args)
        jax.block_until_ready(out)
        self.note_window(site, time.perf_counter() - t0, jitfn, sigs0,
                         args)
        return out

    # -- export --------------------------------------------------------
    def _get_physics(self) -> tuple:
        """(device_kind, peak_flops, hbm_bytes_s); cached once a real
        device is visible, defaults before jax is up."""
        if self._physics is not None:
            return self._physics
        dev = _device0()
        if dev is None:
            return ("unknown", 1e12, 50e9)
        phys = (getattr(dev, "device_kind", "") or dev.platform,
                device_peak_flops(dev), device_hbm_bytes_s(dev))
        self._physics = phys
        return phys

    def _row(self, site: str, s: dict) -> dict:
        _, peak, bw = self._get_physics()
        with self._lock:
            samples = list(s["samples"])
            # dstpu-lint: disable-next-line=DSTPU006 -- hbm_bytes is the /profilez row KEY, not a registry metric
            flops, hbm_bytes = s["flops"], s["hbm_bytes"]
            calls, src = s["calls"], s["costs_src"]
        ms = statistics.median(samples) if samples else None
        # dstpu-lint: disable-next-line=DSTPU006 -- hbm_bytes is the /profilez row KEY, not a registry metric
        row = {"site": site, "flops": flops, "hbm_bytes": hbm_bytes,
               "measured_ms": None if ms is None else round(ms, 4),
               "calls": calls, "costs_src": src,
               "mfu": None, "bw_frac": None}
        if ms is None:
            row["verdict"] = "unmeasured"
        elif flops is None:
            row["verdict"] = "uninstrumented"
        else:
            rl = roofline(flops, hbm_bytes or 0.0, ms / 1000.0, peak, bw)
            # 9 decimals: CPU-mesh mfus sit at 1e-4..1e-6 and must stay
            # recomputable from the row's own fields to ~1e-3 relative
            row["mfu"] = round(rl["mfu"], 9)
            row["bw_frac"] = round(rl["bw_frac"], 9)
            row["verdict"] = rl["verdict"]
        return row

    def snapshot(self) -> dict:
        """The ``/profilez`` payload: device physics + one row per
        site, measured rows first (slowest first)."""
        kind, peak, bw = self._get_physics()
        with self._lock:
            sites = list(self._sites.items())
        rows = [self._row(site, s) for site, s in sites]
        rows.sort(key=lambda r: (r["measured_ms"] is None,
                                 -(r["measured_ms"] or 0.0)))
        return {"enabled": self.enabled(), "device": kind,
                "peak_flops": peak, "hbm_bytes_s": bw,
                "sample_every": self.sample_every(), "rows": rows}

    def verdicts(self) -> dict:
        """{site: verdict} over MEASURED rows only — the anomaly
        plane's drift-detector input."""
        snap = self.snapshot()
        return {r["site"]: r["verdict"] for r in snap["rows"]
                if r["measured_ms"] is not None
                and r["verdict"] not in ("unmeasured", "uninstrumented")}

    def status(self) -> dict:
        """Compact ``/statusz`` ``attribution`` section."""
        snap = self.snapshot()
        measured = [r for r in snap["rows"] if r["measured_ms"] is not None]
        return {"enabled": snap["enabled"], "device": snap["device"],
                "sites": len(snap["rows"]), "measured": len(measured),
                "top": [{k: r[k] for k in
                         ("site", "verdict", "measured_ms", "mfu",
                          "bw_frac")} for r in measured[:5]]}

    def clear(self) -> None:
        """Drop every site (test isolation helper)."""
        with self._lock:
            self._sites.clear()
            self._tick_counts.clear()
            self._cost_failed.clear()
            self._first_skipped.clear()
            self._physics = None


_default: Optional[AttributionPlane] = None


def get_plane() -> AttributionPlane:
    global _default
    if _default is None:
        _default = AttributionPlane()
    return _default


# module-level conveniences over the default plane ----------------------
def enabled() -> bool:
    return get_plane().enabled()


def enable(on: Optional[bool] = True) -> None:
    get_plane().enable(on)


def should_sample(site: str) -> bool:
    return get_plane().should_sample(site)


def note_compiled(compiled, site: str, src: str = "aot") -> Optional[dict]:
    return get_plane().note_compiled(compiled, site, src=src)


def note_measured(site: str, wall_s: float, calls: int = 1) -> None:
    get_plane().note_measured(site, wall_s, calls=calls)


def note_window(site: str, wall_s: float, jitfn=None, sigs_before=None,
                args: tuple = ()) -> bool:
    return get_plane().note_window(site, wall_s, jitfn, sigs_before, args)


def ensure_costs(site: str, jitfn, *args) -> None:
    get_plane().ensure_costs(site, jitfn, *args)


def timed_jit_call(site: str, jitfn, *args):
    return get_plane().timed_jit_call(site, jitfn, *args)


def snapshot() -> dict:
    return get_plane().snapshot()


def status() -> dict:
    return get_plane().status()


_installed = False


def install() -> AttributionPlane:
    """Register the ``/statusz`` section; idempotent (telemetry
    import)."""
    global _installed
    plane = get_plane()
    if not _installed:
        from . import exporter as _exporter

        # resolve the singleton at CALL time: tests (and a future
        # reset) may swap the default plane after install
        _exporter.register_status_provider(
            "attribution", lambda: get_plane().status())
        _installed = True
    return plane


# -- on-demand jax.profiler capture -------------------------------------
_capture_lock = threading.Lock()


def capture_trace(duration_ms: int = 1000,
                  logdir: Optional[str] = None) -> Optional[str]:
    """Capture a ``jax.profiler`` device trace for ``duration_ms`` while
    the workload keeps running (serving ticks on other threads land in
    the capture).  Returns the trace directory (None when a capture is
    already in flight or jax is not up).  Wired to
    ``/profilez?capture_ms=N``; the result opens in TensorBoard /
    Perfetto."""
    jax = sys.modules.get("jax")
    if jax is None:
        return None
    if not _capture_lock.acquire(blocking=False):
        return None          # one capture at a time
    try:
        if logdir is None:
            base = os.environ.get(_registry.METRICS_DIR_ENV) or "."
            logdir = os.path.join(base, "jax_profile")
        os.makedirs(logdir, exist_ok=True)
        jax.profiler.start_trace(logdir)
        time.sleep(max(0, int(duration_ms)) / 1000.0)
        jax.profiler.stop_trace()
        logger.info(f"attribution: jax profiler trace captured to "
                    f"{logdir} ({duration_ms} ms)")
        return logdir
    except Exception as e:
        logger.warning(f"attribution: trace capture failed: {e!r}")
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        return None
    finally:
        _capture_lock.release()
