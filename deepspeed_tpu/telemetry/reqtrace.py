"""Request-scoped distributed tracing: per-request span trees with
tail-based retention and a ``/tracez`` surface.

The stack can say *what* is slow (attribution roofline verdicts), *that*
SLOs burn (anomaly detectors) and *which replica* is hot (``/fleetz``) —
but not "show me everything that happened to request X".  The lifecycle
observers (``telemetry/loadgen.py``) emit flat per-request events and the
Chrome-trace spans (``telemetry/trace.py``) are process-scoped with no
request identity.  This module is the Dapper-style per-request plane:

- :class:`TraceContext` — a 128-bit trace id + 64-bit span ids,
  DETERMINISTIC from the request uid (replaying a seeded loadgen trace
  reproduces the same trace ids), carried across processes in the
  W3C ``traceparent`` header format
  (``00-<32 hex trace id>-<16 hex span id>-<01|00>``) — the propagation
  contract the multi-replica router inherits
  (``ContinuousBatcher.submit(..., trace_context=...)``).
- :class:`RequestTracer` — a batcher lifecycle observer
  (``add_lifecycle_observer``) turning the event stream into a span
  tree per request: ``request`` (root, submit → retire) with children
  ``queue_wait`` (submit → prefill start), ``prefill`` (with
  prefix-cache hit tokens and batch co-members as attributes),
  ``place`` (first token → slot placement: the parked wait), and one
  ``decode``/``verify`` span per emit window (token count + tick
  attributes).  Detached = zero cost (the batcher's observer list is
  empty and ``_note_lifecycle`` short-circuits); attached, every cost
  is host-side dict/list work at window boundaries — no new device
  syncs anywhere near ``step``/``_spec_tick``/``_prefill*``.
- **Tail-based retention** — head sampling (``DSTPU_REQTRACE_SAMPLE``,
  default 1-in-16, decided deterministically from the trace id) bounds
  steady-state memory, but retirement ALWAYS promotes SLO-violating
  (the retire-time ``slo_ok`` tag) and alert-coincident requests into
  a separate bounded ring — sampling can never hide exactly the
  requests a tail-latency investigation needs.  Promoted and sampled
  traces live in distinct rings so a burst of sampled traffic cannot
  evict the violations.
- Export three ways: ``/tracez`` on the per-rank exporter (index of
  retained traces + per-trace JSON), Perfetto/Chrome-trace JSON
  (:func:`chrome_trace` — the same event format and time axis as
  ``trace.py``, so request traces and process spans open in ONE viewer
  timeline), and the fleet stitcher (``fleet.stitch_tracez`` /
  ``FleetView.stitched_traces()``) merging spans sharing a trace id
  across replicas.

Enable per batcher (``RequestTracer(...).attach(batcher)`` /
:func:`install`) or process-wide via ``DSTPU_REQTRACE=1`` (every
``ContinuousBatcher`` attaches the module tracer at construction).
Off by default.
"""
from __future__ import annotations

import hashlib
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ..utils.logging import logger
from . import registry as _registry
from . import trace as _trace

__all__ = [
    "TraceContext", "parse_traceparent", "RequestTracer",
    "chrome_events", "chrome_trace", "save_chrome_trace",
    "get_tracer", "install", "uninstall", "maybe_attach", "flight_index",
    "REQTRACE_ENV", "REQTRACE_SAMPLE_ENV", "REQTRACE_RING_ENV",
    "REQTRACE_SEED_ENV",
]

REQTRACE_ENV = "DSTPU_REQTRACE"
REQTRACE_SAMPLE_ENV = "DSTPU_REQTRACE_SAMPLE"
REQTRACE_RING_ENV = "DSTPU_REQTRACE_RING"
REQTRACE_SEED_ENV = "DSTPU_REQTRACE_SEED"

_DEFAULT_SAMPLE = 16        # head-sample 1 in N (1 = trace everything)
_DEFAULT_RING = 256         # retained traces per ring (sampled/promoted)
_MAX_LIVE = 4096            # in-flight state cap (a lost retire must
                            # not leak unboundedly)


# ----------------------------------------------------------------------
# trace context + propagation
# ----------------------------------------------------------------------
class TraceContext:
    """128-bit trace id + 64-bit span id (+ optional parent span id),
    hex-encoded; ``sampled`` is the head-sampling decision, which
    PROPAGATES (a downstream replica must not re-roll the dice and
    split the trace)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "sampled")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str] = None, sampled: bool = True):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.sampled = bool(sampled)

    @classmethod
    def from_uid(cls, uid: int, seed=0,
                 sample: int = _DEFAULT_SAMPLE) -> "TraceContext":
        """Deterministic context for a locally-submitted request: the
        trace id, root span id AND the head-sampling decision are pure
        functions of ``(seed, uid)`` — replaying a seeded loadgen trace
        reproduces identical ids, so a regression report can name the
        same trace across runs.  ``seed`` may be any str()-able value;
        the env-attached tracer defaults to a per-process ``rank:pid``
        seed so two replicas' independent uid counters can never mint
        the SAME trace id (the fleet stitcher keys on trace id — a
        collision would merge two unrelated requests into one fake
        cross-replica trace)."""
        d = hashlib.sha256(f"dstpu-reqtrace:{seed}:{uid}".encode()).digest()
        sampled = sample <= 1 or \
            int.from_bytes(d[24:28], "big") % max(1, int(sample)) == 0
        return cls(d[:16].hex(), d[16:24].hex(), None, sampled)

    def child_span_id(self, n: int) -> str:
        """Deterministic n-th child span id under this context's span."""
        return hashlib.sha256(
            f"{self.trace_id}:{self.span_id}:{n}".encode()).digest()[:8].hex()

    def to_traceparent(self) -> str:
        """W3C ``traceparent`` form: ``00-<trace>-<span>-<flags>``
        (flag bit 0 = sampled).  THE cross-process propagation format:
        the item-2 router forwards this string with the request and the
        receiving replica's spans join the same trace."""
        return (f"00-{self.trace_id}-{self.span_id}-"
                f"{'01' if self.sampled else '00'}")

    def to_dict(self) -> dict:
        return {"traceparent": self.to_traceparent()}

    def __eq__(self, other) -> bool:
        return isinstance(other, TraceContext) and \
            (self.trace_id, self.span_id, self.parent_id, self.sampled) == \
            (other.trace_id, other.span_id, other.parent_id, other.sampled)

    def __repr__(self) -> str:
        return f"TraceContext({self.to_traceparent()!r})"


def parse_traceparent(value) -> Optional[TraceContext]:
    """Parse a ``traceparent`` string (or a ``{"traceparent": ...}``
    dict, the router's JSON-friendly form) into a context whose
    ``parent_id`` is the INCOMING span id — spans created here become
    its children.  Returns None on anything malformed (a bad header
    must degrade to "new local trace", never break submission)."""
    if isinstance(value, TraceContext):
        return value
    if isinstance(value, dict):
        value = value.get("traceparent")
    if not isinstance(value, str):
        return None
    parts = value.strip().lower().split("-")
    if len(parts) != 4:
        return None
    version, trace_id, span_id, flags = parts
    if len(version) != 2 or len(trace_id) != 32 or len(span_id) != 16 \
            or len(flags) != 2:
        return None
    try:
        int(trace_id, 16), int(span_id, 16), int(flags, 16)
    except ValueError:
        return None
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None
    # the local root span id is derived deterministically from the
    # incoming edge, so the same hop replayed yields the same span id
    local = hashlib.sha256(
        f"{trace_id}:{span_id}:hop".encode()).digest()[:8].hex()
    return TraceContext(trace_id, local, parent_id=span_id,
                        sampled=bool(int(flags, 16) & 1))


# ----------------------------------------------------------------------
# the tracer (a batcher lifecycle observer)
# ----------------------------------------------------------------------
class _Live:
    """In-flight per-request state between submit and retire."""

    __slots__ = ("uid", "ctx", "t_submit", "t_prefill", "t_first",
                 "t_place", "t_cursor", "spans", "n_children", "pf_attrs")

    def __init__(self, uid: int, ctx: TraceContext, t_submit: float):
        self.uid = uid
        self.ctx = ctx
        self.t_submit = t_submit
        self.t_prefill: Optional[float] = None
        self.t_first: Optional[float] = None
        self.t_place: Optional[float] = None
        # where the next decode/verify window span starts
        self.t_cursor: Optional[float] = None
        self.spans: List[dict] = []
        self.n_children = 0
        # prefill_start extras held until first_token closes the span
        self.pf_attrs: dict = {}


class RequestTracer:
    """Per-request span collection + tail-based retention.

    Attach to a batcher with :meth:`attach` (one tracer per batcher —
    request uids are only unique within a batcher).  Thread-safe: the
    serving thread appends, ``/tracez`` scrapes snapshot under the same
    lock."""

    def __init__(self, sample: int = _DEFAULT_SAMPLE,
                 ring: int = _DEFAULT_RING, seed=0,
                 alert_fn: Optional[Callable[[], List[str]]] = None):
        self.sample = max(1, int(sample))
        self.ring = max(1, int(ring))
        # seed=None → per-process "rank:pid": N replicas with identical
        # uid counters mint DISTINCT trace ids (a collision would make
        # the fleet stitcher fuse unrelated requests).  Pass an explicit
        # seed for reproducible ids (seeded loadgen replays).
        self.seed = f"{_registry._rank()}:{os.getpid()}" if seed is None \
            else seed
        # injectable for tests; the default asks the anomaly engine
        # which rules are firing at retirement (alert-coincident
        # requests are promoted even when unsampled)
        self._alert_fn = alert_fn
        self._lock = threading.Lock()
        self._live: "Dict[int, _Live]" = {}
        # two rings: head-sampled traces churn with traffic, promoted
        # (SLO-violating / alert-coincident) traces must survive that
        # churn — one shared ring would let 256 sampled requests evict
        # the one violation the investigation needs
        self._sampled: deque = deque(maxlen=self.ring)
        self._promoted: deque = deque(maxlen=self.ring)
        self._removers: List[Callable[[], None]] = []
        self._m_traced = _registry.counter(
            "reqtrace_requests_traced_total",
            "requests whose lifecycle was observed by the request tracer")
        self._m_retained = _registry.counter(
            "reqtrace_retained_total",
            "request traces retained at retirement, by retention reason",
            labelnames=("reason",))
        self._m_dropped = _registry.counter(
            "reqtrace_dropped_total",
            "request traces dropped at retirement (unsampled, SLO met, "
            "no coincident alert)")
        self._m_ring = _registry.gauge(
            "reqtrace_retained_traces",
            "retained request traces currently held (both rings)")

    # -- batcher wiring -------------------------------------------------
    def attach(self, batcher) -> Callable[[], None]:
        """Register as a lifecycle observer; returns (and remembers)
        the remover."""
        remove = batcher.add_lifecycle_observer(self)
        self._removers.append(remove)
        return remove

    def detach(self) -> None:
        for remove in self._removers:
            try:
                remove()
            except Exception:
                pass
        self._removers.clear()

    # -- the observer (called by ContinuousBatcher._note_lifecycle) ----
    def __call__(self, t: float, uid: int, event: str, extra: dict) -> None:
        with self._lock:
            if event == "submit":
                self._on_submit(t, uid, extra)
                return
            live = self._live.get(uid)
            if live is None:
                return            # attached mid-flight: no submit seen
            if event == "prefill_start":
                live.t_prefill = t
                # the prefix-cache outcome and the batch co-members are
                # the prefill span's attributes — closed at first_token
                live.pf_attrs = {
                    "hit_tokens": extra.get("hit_tokens"),
                    "prefill_tokens": extra.get("prefill_tokens"),
                    "batch": extra.get("batch"),
                    "batch_uids": list(extra.get("batch_uids") or ()) or
                    None,
                }
                self._close(live, "queue_wait", live.t_submit, t, {})
            elif event == "first_token":
                live.t_first = t
                live.t_cursor = t
                if live.t_prefill is not None:
                    self._close(live, "prefill", live.t_prefill, t,
                                live.pf_attrs)
            elif event == "place":
                live.t_place = t
                t0 = live.t_first if live.t_first is not None \
                    else live.t_submit
                self._close(live, "place", t0, t,
                            {"slot": extra.get("slot")})
                live.t_cursor = t
            elif event == "emit":
                t0 = live.t_cursor if live.t_cursor is not None \
                    else live.t_submit
                live.t_cursor = t
                self._close(live, str(extra.get("kind", "decode")), t0, t,
                            {"tokens": int(extra.get("n", 0)),
                             "tick": extra.get("tick")})
            elif event == "retire":
                self._on_retire(t, uid, live, extra)

    def _on_submit(self, t: float, uid: int, extra: dict) -> None:
        ctx = None
        tc = extra.get("trace_context")
        if tc is not None:
            ctx = parse_traceparent(tc)
            if ctx is None:
                logger.warning(
                    f"reqtrace: malformed trace_context for uid {uid}: "
                    f"{tc!r}; starting a fresh local trace")
        if ctx is None:
            ctx = TraceContext.from_uid(uid, seed=self.seed,
                                        sample=self.sample)
        if len(self._live) >= _MAX_LIVE:
            # a request whose retire we never see (observer removed and
            # re-added mid-flight) must not leak state forever
            self._live.pop(next(iter(self._live)))
        self._live[uid] = _Live(uid, ctx, t)
        self._m_traced.inc()

    def _close(self, live: _Live, name: str, t0: float, t1: float,
               attrs: dict) -> None:
        live.n_children += 1
        span = {
            "trace_id": live.ctx.trace_id,
            "span_id": live.ctx.child_span_id(live.n_children),
            "parent_id": live.ctx.span_id,
            "name": name,
            "t0_s": t0,
            "t1_s": t1,
            "attrs": {k: v for k, v in attrs.items() if v is not None},
        }
        live.spans.append(span)

    def _active_alerts(self) -> List[str]:
        if self._alert_fn is not None:
            try:
                return list(self._alert_fn())
            except Exception:
                return []
        try:
            from . import anomaly as _anomaly

            return sorted({a.get("rule", "?")
                           for a in _anomaly.get_engine().active().values()})
        except Exception:
            return []

    def _on_retire(self, t: float, uid: int, live: _Live,
                   extra: dict) -> None:
        self._live.pop(uid, None)
        slo_ok = extra.get("slo_ok")
        alerts = self._active_alerts()
        if slo_ok is False:
            reason = "slo_violation"
        elif alerts:
            reason = "alert"
        elif live.ctx.sampled:
            reason = "sampled"
        else:
            self._m_dropped.inc()
            return
        root = {
            "trace_id": live.ctx.trace_id,
            "span_id": live.ctx.span_id,
            "parent_id": live.ctx.parent_id,
            "name": "request",
            "t0_s": live.t_submit,
            "t1_s": t,
            "attrs": {k: extra.get(k) for k in
                      ("n_out", "ttft_ms", "tpot_ms", "slo_ok")
                      if extra.get(k) is not None},
        }
        now_unix = time.time()
        payload = {
            "trace_id": live.ctx.trace_id,
            "uid": uid,
            "traceparent": live.ctx.to_traceparent(),
            "retained": reason,
            "slo_ok": slo_ok,
            "n_out": extra.get("n_out"),
            "ttft_ms": extra.get("ttft_ms"),
            "tpot_ms": extra.get("tpot_ms"),
            "alerts": alerts,
            "t_unix": now_unix,
            "rank": _registry._rank(),
            "pid": os.getpid(),
            # map span perf_counter seconds onto the unix axis: the
            # fleet stitcher aligns spans from replicas whose
            # perf_counter origins are unrelated
            "clock_offset_s": now_unix - t,
            "spans": [root] + live.spans,
        }
        (self._promoted if reason != "sampled" else
         self._sampled).append(payload)
        self._m_retained.labels(reason=reason).inc()
        self._m_ring.set(float(len(self._sampled) + len(self._promoted)))

    # -- read side ------------------------------------------------------
    @staticmethod
    def _summary(tr: dict) -> dict:
        walls: Dict[str, float] = {}
        for s in tr["spans"]:
            if s["name"] == "request":
                continue
            walls[s["name"]] = round(
                walls.get(s["name"], 0.0)
                + (s["t1_s"] - s["t0_s"]) * 1e3, 3)
        return {
            "trace_id": tr["trace_id"], "uid": tr["uid"],
            "retained": tr["retained"], "slo_ok": tr["slo_ok"],
            "n_out": tr["n_out"], "ttft_ms": tr["ttft_ms"],
            "tpot_ms": tr["tpot_ms"], "t_unix": tr["t_unix"],
            "alerts": tr.get("alerts") or [],
            "span_walls_ms": walls,
            "n_spans": len(tr["spans"]),
        }

    def _all_retained(self) -> List[dict]:
        """Promoted first (the traces an investigation needs), then
        sampled — both newest-first."""
        return list(reversed(self._promoted)) + list(reversed(self._sampled))

    def index(self) -> dict:
        """The ``/tracez`` index: summaries of every retained trace."""
        with self._lock:
            retained = self._all_retained()
            live = len(self._live)
        return {
            "enabled": True,
            "sample": self.sample,
            "ring": self.ring,
            "live": live,
            "promoted": sum(1 for t in retained
                            if t["retained"] != "sampled"),
            "retained": [self._summary(t) for t in retained],
        }

    def payload(self, full: bool = False) -> dict:
        """``/tracez`` body: the index, plus every retained trace's full
        span list under ``traces`` when ``full`` (the fleet stitcher's
        fetch)."""
        out = self.index()
        if full:
            with self._lock:
                out["traces"] = [dict(t) for t in self._all_retained()]
        return out

    def get_trace(self, trace_id: str) -> Optional[dict]:
        """Full payload for one retained trace (newest match wins —
        a cross-replica hop may retire twice under one id locally only
        when uids collide, which :meth:`attach` scoping prevents)."""
        with self._lock:
            for tr in self._all_retained():
                if tr["trace_id"] == trace_id:
                    return dict(tr)
        return None

    def traces(self) -> List[dict]:
        with self._lock:
            return [dict(t) for t in self._all_retained()]

    def clear(self) -> None:
        with self._lock:
            self._live.clear()
            self._sampled.clear()
            self._promoted.clear()
            self._m_ring.set(0.0)

    def _status(self) -> dict:
        """``/statusz`` ``reqtrace`` section."""
        with self._lock:
            return {
                "sample": self.sample,
                "ring": self.ring,
                "live": len(self._live),
                "retained_sampled": len(self._sampled),
                "retained_promoted": len(self._promoted),
            }


# ----------------------------------------------------------------------
# Perfetto / Chrome-trace export (trace.py's event format + time axis)
# ----------------------------------------------------------------------
def chrome_events(tr: dict) -> List[dict]:
    """One retained trace's spans as Chrome-trace ``X`` events on the
    SAME microsecond axis ``trace.py`` writes (``perf_to_trace_us``), so
    a request trace and the process span file (``DSTPU_TRACE``) load
    into one Perfetto timeline.  The request uid is the ``tid`` — each
    request renders as its own named track."""
    pid = tr.get("pid", os.getpid())
    tid = int(tr.get("uid", 0))
    events: List[dict] = [{
        "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
        "args": {"name": f"req uid={tr.get('uid')} "
                         f"trace={tr['trace_id'][:12]}"},
    }]
    for s in tr["spans"]:
        events.append({
            "name": s["name"], "ph": "X",
            "ts": _trace.perf_to_trace_us(s["t0_s"]),
            "dur": max(0.0, (s["t1_s"] - s["t0_s"]) * 1e6),
            "pid": pid, "tid": tid,
            "args": {"trace_id": s["trace_id"], "span_id": s["span_id"],
                     "parent_id": s["parent_id"], "uid": tr.get("uid"),
                     **(s.get("attrs") or {})},
        })
    return events


def chrome_trace(traces) -> dict:
    """Chrome-trace JSON object (the ``traceEvents`` wrapper form, same
    as ``trace.to_json()``) for one retained trace dict or a list of
    them."""
    if isinstance(traces, dict):
        traces = [traces]
    events: List[dict] = []
    for tr in traces:
        events.extend(chrome_events(tr))
    return {"displayTimeUnit": "ms", "traceEvents": events}


def save_chrome_trace(path: str, traces) -> str:
    """Write ``chrome_trace(traces)`` to ``path`` (atomic rename);
    loadable in ``ui.perfetto.dev`` / ``chrome://tracing`` as-is."""
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(chrome_trace(traces), fh)
    os.replace(tmp, path)
    return path


# ----------------------------------------------------------------------
# module singleton + env wiring
# ----------------------------------------------------------------------
_tracer: Optional[RequestTracer] = None


def get_tracer() -> Optional[RequestTracer]:
    return _tracer


def install(batcher=None, **kwargs) -> RequestTracer:
    """Create (or replace) the module tracer — the instance ``/tracez``
    and the flight dump read by default — and attach it to ``batcher``
    when given."""
    global _tracer
    if _tracer is not None:
        _tracer.detach()
    _tracer = RequestTracer(**kwargs)
    if batcher is not None:
        _tracer.attach(batcher)
    from . import exporter as _exporter

    _exporter.register_status_owner("reqtrace", _tracer, "_status")
    return _tracer


def uninstall() -> None:
    global _tracer
    if _tracer is not None:
        _tracer.detach()
    _tracer = None


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        logger.warning(f"reqtrace: ignoring non-integer {name}={raw!r}")
        return default


def maybe_attach(batcher) -> Optional[RequestTracer]:
    """Attach the env-configured module tracer to a new batcher.

    Called from ``ContinuousBatcher.__init__``; a no-op (None) unless
    ``DSTPU_REQTRACE=1`` — the default-off contract: no observer is
    registered, so the serving paths' ``_note_lifecycle`` short-circuit
    keeps the hot loop cost at one truthiness check.

    The module tracer follows the NEWEST batcher: uids are only unique
    within one batcher, so feeding the tracer from two at once would
    let batcher B's uid 0 overwrite batcher A's in-flight state and
    produce span trees mixing two requests (the one-tracer-per-batcher
    invariant).  A rebuilt engine+batcher (the bench ``_retry``
    pattern) therefore hands tracing over cleanly; run explicit
    ``RequestTracer().attach(...)`` instances for genuinely concurrent
    pools.  The tracer seed defaults to per-process ``rank:pid``
    (``DSTPU_REQTRACE_SEED`` overrides for reproducible ids)."""
    if os.environ.get(REQTRACE_ENV, "") in ("", "0"):
        return None
    global _tracer
    if _tracer is None:
        seed_env = os.environ.get(REQTRACE_SEED_ENV)
        install(sample=_env_int(REQTRACE_SAMPLE_ENV, _DEFAULT_SAMPLE),
                ring=_env_int(REQTRACE_RING_ENV, _DEFAULT_RING),
                seed=seed_env if seed_env else None)
    else:
        _tracer.detach()
    _tracer.attach(batcher)
    return _tracer


def flight_index(max_promoted: int = 16) -> Optional[dict]:
    """The flight dump's ``reqtrace`` entry: the retained-trace index
    with the promoted (SLO-violating / alert-coincident) summaries
    capped — forensics wants the tail, not the whole ring.  None when
    no tracer is installed or nothing was retained."""
    t = _tracer
    if t is None:
        return None
    idx = t.index()
    if not idx["retained"]:
        return None
    promoted = [s for s in idx["retained"] if s["retained"] != "sampled"]
    idx["retained"] = promoted[:max_promoted] + \
        [s for s in idx["retained"]
         if s["retained"] == "sampled"][:max_promoted]
    return idx
