"""Traffic-trace load harness: seeded trace generation, replay against
the continuous batcher, and goodput-under-SLO reporting.

Every serving feature so far (fused decode kernels, prefix-cache reuse,
speculative decoding) has been judged by one-shot probes — steady-state
single-stream throughput, which is NOT what "heavy traffic from millions
of users" looks like.  Serving-systems work evaluates with trace-driven
load and p99-bounded goodput: requests arrive on their own clock
(Poisson or bursty), prompt lengths are mixed, a fraction of traffic
shares a system prompt, and generation lengths are long-tailed.  A
request that finishes but blew its latency budget is not useful work.

This module provides the measurement substrate:

- :func:`generate_trace` — a DETERMINISTIC, seeded traffic trace
  (:class:`TraceConfig` → :class:`Trace`): Poisson or Markov-modulated
  bursty arrivals, a prompt-length mixture, an exactly-honored
  shared-prefix ratio (exercises the radix prefix cache), and Zipf
  long-tail generation lengths.  Same seed ⇒ byte-identical trace
  (``Trace.sha256()`` is the regression-gate anchor).
- :func:`replay` — drive a ``ContinuousBatcher`` with the trace in open
  loop (arrivals never wait on completions), collecting a per-request
  lifecycle waterfall (submit → queue → prefix-cache hit/miss → prefill
  → first token → decode/verify → retire) via the batcher's lifecycle
  observer hook, a queue-depth timeline, and per-phase goodput
  attribution from :mod:`.goodput`.
- :func:`compute_goodput` — **goodput under SLO**: tokens/s and
  requests/s counted only for requests meeting the TTFT/TPOT bounds,
  plus SLO attainment % and tail percentiles (the same percentile
  convention ``ContinuousBatcher.latency_stats`` uses, so /statusz and
  the load report agree).
- :func:`calibrate_slo` — machine-relative SLO bounds (a multiple of
  the box's own unloaded TTFT/TPOT), so the CI gate is portable across
  runner speeds while still catching scheduling regressions.
- :func:`check_baseline` — the regression gate: exact-match the trace
  hash and total output tokens (determinism drift is a failure in its
  own right), and fail when SLO attainment or the goodput token ratio
  drops beyond tolerance vs a checked-in baseline
  (``SERVE_LOAD_BASELINE.json``; see ``scripts/loadgen.py --gate``).

CLI: ``scripts/loadgen.py`` (see ``--help``); compact bench block:
``bench.py --mode serving_load``.
"""
from __future__ import annotations

import dataclasses
import hashlib
import heapq
import json
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from . import goodput as goodput_mod
from . import registry as telemetry_registry

__all__ = [
    "TraceConfig", "TraceRequest", "Trace", "generate_trace",
    "trace_config_from_dict", "SLOConfig", "RetryConfig",
    "compute_goodput", "pct", "LifecycleCollector", "LoadReport",
    "replay", "calibrate_slo", "check_baseline",
]


# ----------------------------------------------------------------------
# trace generation
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class TraceConfig:
    """Seeded workload description.  Every field is part of the trace
    identity: the generator is a pure function of this config, and the
    regression baseline embeds the config so the gate always replays
    the exact trace it was recorded against."""

    seed: int = 0
    n_requests: int = 32
    # arrival process: "poisson" (exponential inter-arrivals at
    # ``rate_rps``) or "bursty" (two-state Markov-modulated Poisson:
    # calm at ``rate_rps``, bursts at ``burst_rate_rps``, switching
    # per-arrival with the enter/exit probabilities)
    arrival: str = "poisson"
    rate_rps: float = 8.0
    burst_rate_rps: Optional[float] = None      # default: 4 × rate_rps
    burst_enter_p: float = 0.08
    burst_exit_p: float = 0.25
    # prompt-length mixture: ((length, weight), ...); per-request jitter
    # of ±``prompt_len_jitter`` (uniform, fractional) around the drawn
    # mode keeps lengths mixed without losing the modes
    prompt_len_mix: Tuple[Tuple[int, float], ...] = (
        (16, 0.5), (48, 0.3), (128, 0.2))
    prompt_len_jitter: float = 0.25
    # exactly round(shared_prefix_ratio * n_requests) requests start
    # with ONE shared prefix of ``shared_prefix_len`` tokens (the
    # radix-prefix-cache workload); membership is a seeded permutation
    shared_prefix_ratio: float = 0.0
    shared_prefix_len: int = 16
    # generation lengths: gen_len_min - 1 + Zipf(a), clamped to
    # [gen_len_min, gen_len_max] — a long tail of big generations over
    # a mass of short ones
    gen_len_min: int = 2
    gen_len_max: int = 64
    gen_len_zipf_a: float = 2.2
    vocab_size: int = 512
    # clamp prompt_len + gen_len to the engine's generation limit; None
    # disables (the replay would raise on an oversized request)
    max_total_len: Optional[int] = None


@dataclasses.dataclass
class TraceRequest:
    idx: int
    arrival_s: float
    prompt: np.ndarray            # (S,) int32
    max_new_tokens: int
    shared_prefix: bool
    regime: str                   # "calm" | "burst"

    def to_jsonable(self) -> dict:
        return {
            "idx": self.idx,
            # float.hex(): byte-exact round-trip — repr-based shortest
            # floats are stable too, but hex makes the determinism
            # contract explicit
            "arrival_s": float(self.arrival_s).hex(),
            "prompt": [int(t) for t in self.prompt],
            "max_new_tokens": int(self.max_new_tokens),
            "shared_prefix": bool(self.shared_prefix),
            "regime": self.regime,
        }


@dataclasses.dataclass
class Trace:
    config: TraceConfig
    requests: List[TraceRequest]

    def to_jsonable(self) -> dict:
        return {"config": dataclasses.asdict(self.config),
                "requests": [r.to_jsonable() for r in self.requests]}

    def to_json(self) -> str:
        return json.dumps(self.to_jsonable(), sort_keys=True,
                          separators=(",", ":"))

    def sha256(self) -> str:
        return hashlib.sha256(self.to_json().encode()).hexdigest()

    @property
    def total_prompt_tokens(self) -> int:
        return sum(len(r.prompt) for r in self.requests)

    @property
    def total_max_new_tokens(self) -> int:
        return sum(r.max_new_tokens for r in self.requests)


def trace_config_from_dict(d: dict) -> TraceConfig:
    """Rebuild a :class:`TraceConfig` from its JSON form (the baseline
    file embeds one so the gate always replays the recorded trace).
    JSON turns the mixture tuples into lists; normalize them back —
    the dataclass must hash/compare equal to the original."""
    kw = dict(d)
    if "prompt_len_mix" in kw:
        kw["prompt_len_mix"] = tuple(
            (int(length), float(weight))
            for length, weight in kw["prompt_len_mix"])
    unknown = set(kw) - {f.name for f in
                         dataclasses.fields(TraceConfig)}
    if unknown:
        raise ValueError(f"unknown TraceConfig fields {sorted(unknown)}")
    return TraceConfig(**kw)


def generate_trace(cfg: TraceConfig) -> Trace:
    """Deterministic trace from ``cfg`` (same config ⇒ byte-identical
    output; see ``Trace.sha256``)."""
    if cfg.arrival not in ("poisson", "bursty"):
        raise ValueError(f"unknown arrival process {cfg.arrival!r}; "
                         f"one of ('poisson', 'bursty')")
    if not cfg.prompt_len_mix:
        raise ValueError("prompt_len_mix must be non-empty")
    if cfg.rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {cfg.rate_rps}")
    if (cfg.shared_prefix_ratio > 0 and cfg.max_total_len is not None
            and cfg.max_total_len < cfg.shared_prefix_len + 2):
        # the truncation below would strip the guaranteed unique suffix
        # token (prompt[:max_total_len-1] of a shared-prefix prompt is a
        # pure prefix slice) — kvreuse's exact-match cap needs a real
        # last token through prefill, so reject rather than silently
        # emit degenerate identical prompts
        raise ValueError(
            f"max_total_len={cfg.max_total_len} leaves no room for a "
            f"unique suffix token + 1 generated token after a "
            f"{cfg.shared_prefix_len}-token shared prefix; need >= "
            f"{cfg.shared_prefix_len + 2}")
    rng = np.random.default_rng(cfg.seed)
    n = int(cfg.n_requests)
    burst_rate = (cfg.burst_rate_rps if cfg.burst_rate_rps is not None
                  else 4.0 * cfg.rate_rps)

    # -- arrivals (one pass; regime switches evaluated per arrival) ----
    arrivals: List[float] = []
    regimes: List[str] = []
    t = 0.0
    state = "calm"
    for _ in range(n):
        rate = cfg.rate_rps if state == "calm" else burst_rate
        t += float(rng.exponential(1.0 / rate))
        arrivals.append(t)
        regimes.append(state)
        if cfg.arrival == "bursty":
            if state == "calm" and rng.random() < cfg.burst_enter_p:
                state = "burst"
            elif state == "burst" and rng.random() < cfg.burst_exit_p:
                state = "calm"

    # -- shared-prefix membership: EXACT count, seeded permutation -----
    k = int(round(cfg.shared_prefix_ratio * n))
    shared_idx = set(int(i) for i in rng.permutation(n)[:k])
    prefix = rng.integers(0, cfg.vocab_size,
                          size=(cfg.shared_prefix_len,)).astype(np.int32)

    # -- per-request prompt/generation shapes --------------------------
    lens, weights = zip(*cfg.prompt_len_mix)
    w = np.asarray(weights, np.float64)
    w = w / w.sum()
    reqs: List[TraceRequest] = []
    for i in range(n):
        mode = int(lens[int(rng.choice(len(lens), p=w))])
        jit = float(rng.uniform(-cfg.prompt_len_jitter,
                                cfg.prompt_len_jitter))
        plen = max(1, int(round(mode * (1.0 + jit))))
        gen = int(cfg.gen_len_min - 1 + rng.zipf(cfg.gen_len_zipf_a))
        gen = max(cfg.gen_len_min, min(cfg.gen_len_max, gen))
        if i in shared_idx:
            # the shared prefix plus a unique suffix; the prompt keeps
            # at least one unique token so exact-match prefix reuse
            # still runs the real last token through prefill
            plen = max(plen, cfg.shared_prefix_len + 1)
            sfx = rng.integers(
                0, cfg.vocab_size,
                size=(plen - cfg.shared_prefix_len,)).astype(np.int32)
            prompt = np.concatenate([prefix, sfx])
        else:
            prompt = rng.integers(0, cfg.vocab_size,
                                  size=(plen,)).astype(np.int32)
        if cfg.max_total_len is not None:
            if len(prompt) >= cfg.max_total_len:
                prompt = prompt[:cfg.max_total_len - 1]
            gen = max(1, min(gen, cfg.max_total_len - len(prompt)))
        reqs.append(TraceRequest(idx=i, arrival_s=arrivals[i],
                                 prompt=prompt, max_new_tokens=gen,
                                 shared_prefix=i in shared_idx,
                                 regime=regimes[i]))
    return Trace(config=cfg, requests=reqs)


# ----------------------------------------------------------------------
# SLO + goodput
# ----------------------------------------------------------------------
@dataclasses.dataclass(frozen=True)
class SLOConfig:
    """p99-style per-request bounds: TTFT (submit → first token) and
    TPOT (first token → retirement, per output token), milliseconds.
    A request meets SLO iff it finished AND both bounds hold (TPOT is
    vacuous for single-token requests)."""

    ttft_ms: float
    tpot_ms: float

    def to_jsonable(self) -> dict:
        return {"ttft_ms": round(self.ttft_ms, 3),
                "tpot_ms": round(self.tpot_ms, 3)}


# the ONE nearest-rank percentile convention, shared with serving's
# latency_stats/statusz (see registry.pct) — re-exported here because
# the load report is where the convention is most visible
pct = telemetry_registry.pct


@dataclasses.dataclass(frozen=True)
class RetryConfig:
    """Closed-loop client behavior for shed requests: a request the
    admission controller rejects is re-submitted after a jittered
    exponential backoff (``backoff_ms * 2^attempt * (1 + jitter*u)``,
    ``u`` seeded-uniform), up to ``max_retries`` attempts.  This is how
    real clients behave behind a shedding front-end — without it the
    harness can only measure open-loop shed rates, not the closed-loop
    goodput an operator actually gets."""

    max_retries: int = 2
    backoff_ms: float = 50.0
    jitter: float = 0.5
    seed: int = 0


def compute_goodput(records: Sequence[dict], slo: SLOConfig,
                    wall_s: float) -> dict:
    """Goodput under SLO over completed-request ``records``.

    Each record needs ``n_out`` (output tokens), ``ttft_ms``, and
    ``tpot_ms`` (None when n_out < 2).  Offered-but-unfinished requests
    should be passed with ``n_out=0, ttft_ms=inf`` — an unfinished
    request is an SLO violation, not a statistical no-show.  Requests
    shed at admission carry ``rejected=True`` (plus ``n_out=0,
    ttft_ms=inf``): they count against SLO attainment exactly like
    unfinished ones — shedding is never free in the headline number —
    and ``slo_attainment_admitted`` reports attainment over the
    admitted subset, so the controller's win for the requests it DID
    serve is visible next to the cost of the sheds."""
    n = len(records)
    met_tokens = 0
    all_tokens = 0
    met = 0
    rejected = 0
    ttfts: List[float] = []
    tpots: List[float] = []
    for r in records:
        if r.get("rejected"):
            rejected += 1
            continue
        n_out = int(r["n_out"])
        all_tokens += n_out
        ttft = float(r["ttft_ms"])
        tpot = r.get("tpot_ms")
        if ttft == ttft and ttft != float("inf"):
            ttfts.append(ttft)
        if tpot is not None and tpot == tpot:
            tpots.append(float(tpot))
        ok = n_out > 0 and ttft <= slo.ttft_ms and \
            (tpot is None or tpot <= slo.tpot_ms)
        if ok:
            met += 1
            met_tokens += n_out
    ttfts.sort()
    tpots.sort()
    wall = max(wall_s, 1e-9)
    return {
        "n_requests": n,
        "slo": slo.to_jsonable(),
        "slo_attainment": round(met / n, 6) if n else None,
        # attainment over the ADMITTED subset (sheds excluded from the
        # denominator HERE ONLY — the headline slo_attainment above
        # counts them as violations)
        "slo_attainment_admitted":
            round(met / (n - rejected), 6) if n - rejected else None,
        "rejected": rejected,
        "slo_met": met,
        "goodput_tok_s": round(met_tokens / wall, 3),
        "goodput_rps": round(met / wall, 4),
        "total_tok_s": round(all_tokens / wall, 3),
        # dstpu-lint: disable-next-line=DSTPU006 -- report JSON key (the gate floor's numerator), not a registry metric; the scrapeable aggregate is loadgen_goodput_tokens_rate
        "goodput_token_ratio":
            round(met_tokens / all_tokens, 6) if all_tokens else None,
        "total_output_tokens": all_tokens,
        "ttft_p50_ms": round(pct(ttfts, 0.50), 3),
        "ttft_p99_ms": round(pct(ttfts, 0.99), 3),
        "tpot_p50_ms": round(pct(tpots, 0.50), 3),
        "tpot_p99_ms": round(pct(tpots, 0.99), 3),
    }


# ----------------------------------------------------------------------
# lifecycle collection (the per-request waterfall)
# ----------------------------------------------------------------------
class LifecycleCollector:
    """Batcher lifecycle observer (``add_lifecycle_observer``): records
    every (t, uid, event, extra) so the report can render per-request
    waterfalls and attribute an SLO violation to a phase."""

    def __init__(self):
        self.events: Dict[int, List[Tuple[float, str, dict]]] = {}

    def __call__(self, t: float, uid: int, event: str, extra: dict) -> None:
        self.events.setdefault(uid, []).append((t, event, dict(extra)))

    def first(self, uid: int, event: str) -> Optional[Tuple[float, dict]]:
        for t, ev, extra in self.events.get(uid, ()):
            if ev == event:
                return t, extra
        return None

    def waterfall(self, uid: int, t0: float) -> dict:
        """Phase boundaries for one request, seconds relative to ``t0``:
        queued (submit → prefill start), prefill (→ first token), decode
        (→ retire), plus prefix-cache hit/miss and decode-vs-verify
        token counts."""
        sub = self.first(uid, "submit")
        pf = self.first(uid, "prefill_start")
        ft = self.first(uid, "first_token")
        ret = self.first(uid, "retire")
        decode_toks = verify_toks = 0
        for _, ev, extra in self.events.get(uid, ()):
            if ev == "emit":
                if extra.get("kind") == "verify":
                    verify_toks += int(extra.get("n", 0))
                else:
                    decode_toks += int(extra.get("n", 0))
        out: dict = {"uid": uid}
        for name, rec in (("submit", sub), ("prefill_start", pf),
                          ("first_token", ft), ("retire", ret)):
            out[f"t_{name}_s"] = \
                None if rec is None else round(rec[0] - t0, 6)
        if pf is not None:
            out["prefix_hit_tokens"] = int(pf[1].get("hit_tokens", 0))
            out["prefill_tokens"] = int(pf[1].get("prefill_tokens", 0))
        if ret is not None:
            out.update({k: ret[1].get(k) for k in
                        ("n_out", "ttft_ms", "tpot_ms", "slo_ok")
                        if k in ret[1]})
        out["decode_tokens"] = decode_toks
        out["verify_tokens"] = verify_toks
        # phase durations (the waterfall bars); None when a boundary is
        # missing (e.g. the request never finished)
        def _dur(a, b):
            if a is None or b is None:
                return None
            return round(b[0] - a[0], 6)

        out["queued_s"] = _dur(sub, pf)
        out["prefill_s"] = _dur(pf, ft)
        out["decode_s"] = _dur(ft, ret)
        return out


# ----------------------------------------------------------------------
# replay
# ----------------------------------------------------------------------
@dataclasses.dataclass
class LoadReport:
    """One replay's results: aggregate goodput-under-SLO + per-request
    waterfalls + queue-depth timeline + host-phase attribution."""

    trace_sha256: str
    trace_config: dict
    slo: dict
    wall_s: float
    goodput: dict
    waterfalls: List[dict]
    queue_timeline: List[dict]
    phases: dict
    completed: int
    offered: int
    rejected: int = 0          # shed at admission (final — post-retry)
    # routed runs (inference/router.py replay_routed): per-replica
    # rollup {name: {requests, hit_tokens, prefill_tokens, sheds,
    # failovers}} and the routed-arm summary (policy, lost, failovers).
    # None for plain single-batcher replays — absent from their tables.
    per_replica: Optional[Dict[str, dict]] = None
    routed: Optional[dict] = None

    def to_jsonable(self) -> dict:
        return dataclasses.asdict(self)

    def table(self) -> str:
        """Human summary (the CLI's stdout)."""
        g = self.goodput
        lines = [
            f"trace {self.trace_sha256[:12]}…  offered {self.offered} "
            f"requests, completed {self.completed}, wall "
            f"{self.wall_s:.2f}s",
            f"SLO: TTFT <= {g['slo']['ttft_ms']:.1f} ms, TPOT <= "
            f"{g['slo']['tpot_ms']:.1f} ms/token",
            f"{'goodput (under SLO)':<24}{g['goodput_tok_s']:>10.1f} tok/s"
            f"  {g['goodput_rps']:>8.2f} req/s",
            f"{'throughput (all)':<24}{g['total_tok_s']:>10.1f} tok/s",
            f"{'SLO attainment':<24}"
            f"{100.0 * (g['slo_attainment'] or 0.0):>9.1f}%"
            f"   ({g['slo_met']}/{g['n_requests']})",
            *([f"{'rejected (shed)':<24}{self.rejected:>10d} requests"
               f"   (admitted attainment "
               f"{100.0 * (g.get('slo_attainment_admitted') or 0.0):.1f}%)"]
              if self.rejected else []),
            f"{'goodput token ratio':<24}"
            # dstpu-lint: disable-next-line=DSTPU006 -- report JSON key read-back, not a registry metric
            f"{(g['goodput_token_ratio'] or 0.0):>10.3f}",
            f"{'TTFT p50/p99':<24}{g['ttft_p50_ms']:>10.1f} /"
            f" {g['ttft_p99_ms']:.1f} ms",
            f"{'TPOT p50/p99':<24}{g['tpot_p50_ms']:>10.2f} /"
            f" {g['tpot_p99_ms']:.2f} ms/token",
        ]
        ph = {k: v for k, v in self.phases.items() if v}
        if ph:
            lines.append("host phases: " + "  ".join(
                f"{k}={v:.2f}s" for k, v in sorted(ph.items())))
        if self.queue_timeline:
            peak = max(s["queued"] for s in self.queue_timeline)
            lines.append(f"peak queue depth: {peak}")
        if self.routed:
            lines.append(
                f"routed ({self.routed.get('policy')}): "
                f"failovers {self.routed.get('failovers', 0)}, lost "
                f"{self.routed.get('lost', 0)}, hit-token ratio "
                # dstpu-lint: disable-next-line=DSTPU006 -- report JSON key read-back, not a registry metric
                f"{g.get('prefix_hit_token_ratio')}")
        if self.per_replica:
            lines.append(f"{'replica':<10} {'requests':>9} "
                         f"{'hit_tok':>8} {'prefill_tok':>12} "
                         f"{'sheds':>6} {'failovers':>10}")
            for name in sorted(self.per_replica):
                pr = self.per_replica[name]
                lines.append(
                    f"{name:<10} {pr.get('requests', 0):>9} "
                    f"{pr.get('hit_tokens', 0):>8} "
                    f"{pr.get('prefill_tokens', 0):>12} "
                    f"{pr.get('sheds', 0):>6} "
                    f"{pr.get('failovers', 0):>10}")
        return "\n".join(lines)

    def format_waterfalls(self, limit: int = 8,
                          links: Optional[Dict[int, str]] = None) -> str:
        """The ``limit`` slowest-TTFT request waterfalls as text bars.

        ``links`` maps request uid → retained-trace file (written by
        ``scripts/loadgen.py --trace-out``): each bar then names the
        Perfetto JSON holding that exact request's span tree, so the
        slowest-TTFT table IS the index into "why was this one slow"."""
        done = [w for w in self.waterfalls if w.get("ttft_ms") is not None]
        done.sort(key=lambda w: -w["ttft_ms"])
        # routed replays attribute each request to the replica that
        # served it — surface the column whenever any row carries one
        routed = any(w.get("replica") for w in self.waterfalls)
        lines = [f"{'uid':>5} {'queued':>9} {'prefill':>9} {'decode':>9} "
                 f"{'ttft_ms':>9} {'tpot_ms':>9} {'tok':>5} {'hit':>5} slo"
                 + ("  replica" if routed else "")
                 + ("  trace" if links else "")]
        for w in done[:limit]:
            def ms(x):
                return "-" if x is None else f"{1e3 * x:9.1f}"
            tpot = w.get("tpot_ms")
            lines.append(
                f"{w['uid']:>5} {ms(w['queued_s'])} {ms(w['prefill_s'])} "
                f"{ms(w['decode_s'])} {w['ttft_ms']:>9.1f} "
                f"{'-' if tpot is None else format(tpot, '9.2f'):>9} "
                f"{w.get('n_out', 0):>5} "
                f"{w.get('prefix_hit_tokens', 0):>5} "
                f"{'ok' if w.get('slo_ok') else 'VIOL'}"
                + (f"  {w.get('replica') or '-'}" if routed else "")
                + (f"  {links.get(w['uid'], '-')}" if links else ""))
        rej = [w for w in self.waterfalls if w.get("rejected")]
        if rej:
            shown = ", ".join(
                f"idx{w['idx']}={w['rejected']}"
                + (f"(x{w['attempts']})" if w.get("attempts") else "")
                for w in rej[:limit])
            lines.append(
                f"rejected (shed): {len(rej)} requests — {shown}"
                + ("…" if len(rej) > limit else ""))
        return "\n".join(lines)


_last_report: Optional[LoadReport] = None


def _loadgen_status() -> Optional[dict]:
    """``/statusz`` ``loadgen`` section: the last replay's aggregate."""
    if _last_report is None:
        return None
    g = _last_report.goodput
    return {
        "trace_sha256": _last_report.trace_sha256,
        "wall_s": round(_last_report.wall_s, 3),
        "offered": _last_report.offered,
        "completed": _last_report.completed,
        "rejected": _last_report.rejected,
        "slo": g["slo"],
        "slo_attainment": g["slo_attainment"],
        "goodput_tok_s": g["goodput_tok_s"],
        "total_tok_s": g["total_tok_s"],
        "ttft_p99_ms": g["ttft_p99_ms"],
        "tpot_p99_ms": g["tpot_p99_ms"],
    }


def replay(batcher, trace: Trace, slo: Optional[SLOConfig], *,
           ticks: int = 4, time_scale: float = 1.0,
           retry=None,
           on_progress: Optional[Callable[[str], None]] = None
           ) -> LoadReport:
    """Replay ``trace`` against ``batcher`` in open loop and report
    goodput under ``slo``.

    Arrivals are driven by the trace's own clock (scaled by
    ``time_scale`` — 2.0 replays a trace at twice its recorded offered
    load): a request is submitted the moment its arrival time passes,
    whether or not the pool has room — queueing delay is part of what
    is being measured.  The batcher steps ``ticks`` decode ticks per
    iteration whenever work is pending and sleeps only when idle before
    the next arrival.

    ``slo=None`` measures without configuring the batcher's retire-time
    tagging (warmup replays use this so throwaway requests don't
    inflate the ``serving_slo_*`` counters); the report then judges
    against effectively-infinite bounds.  A real ``slo`` is installed
    via ``set_slo`` for the duration and the previous bounds restored
    after — a load run must not permanently reconfigure a deployment's
    batcher.

    ``retry`` (a :class:`RetryConfig` or kwargs dict) enables
    closed-loop client behavior against an admission-controlled
    batcher: a SHED request is re-submitted after a seeded jittered
    backoff, up to ``max_retries`` times.  A request whose final
    attempt is still shed lands as a ``rejected`` outcome — counted
    against SLO attainment, never a no-show."""
    judge = slo if slo is not None else SLOConfig(ttft_ms=1e12,
                                                 tpot_ms=1e12)
    retry_cfg = None
    if retry is not None:
        retry_cfg = retry if isinstance(retry, RetryConfig) \
            else RetryConfig(**retry)
    retry_rng = np.random.default_rng(retry_cfg.seed) \
        if retry_cfg is not None else None
    reqs = sorted(trace.requests, key=lambda r: r.arrival_s)
    collector = LifecycleCollector()
    remove = batcher.add_lifecycle_observer(collector)
    prev_slo = (batcher._slo_ttft_ms, batcher._slo_tpot_ms)
    if slo is not None:
        batcher.set_slo(slo.ttft_ms, slo.tpot_ms)
    gp0 = goodput_mod.summary()
    timeline: List[dict] = []
    uid_by_idx: Dict[int, int] = {}
    attempts: Dict[int, int] = {}
    retries: List[tuple] = []      # (due wall time, trace idx) heap
    t0 = time.perf_counter()
    rej_live = getattr(batcher, "rejected", {})   # mutated in place
    watched: Dict[int, int] = {}   # admitted uid -> idx (async sheds)

    def _schedule_retry(idx: int) -> None:
        a = attempts[idx] - 1
        delay = (retry_cfg.backoff_ms / 1e3) * (2 ** a) \
            * (1.0 + retry_cfg.jitter * float(retry_rng.random()))
        heapq.heappush(retries, (time.perf_counter() + delay, idx))

    def _submit(r) -> None:
        uid = batcher.submit(r.prompt, max_new_tokens=r.max_new_tokens)
        uid_by_idx[r.idx] = uid
        attempts[r.idx] = attempts.get(r.idx, 0) + 1
        if retry_cfg is None:
            return
        if uid in rej_live:            # shed synchronously at submit
            if attempts[r.idx] <= retry_cfg.max_retries:
                _schedule_retry(r.idx)
        else:
            watched[uid] = r.idx       # may still shed asynchronously

    def _sweep_async_sheds() -> None:
        """A request admitted at submit can still be shed LATER (queue
        eviction by a higher-priority arrival, the deadline sweep,
        drain) — the closed-loop client must retry those too, not just
        the synchronous submit-time rejections."""
        for uid in [u for u in watched if u in rej_live]:
            ridx = watched.pop(uid)
            if attempts[ridx] <= retry_cfg.max_retries:
                _schedule_retry(ridx)

    req_by_idx = {r.idx: r for r in reqs}
    try:
        i = 0
        last_progress = 0
        n = len(reqs)
        while i < n or retries or batcher.pending or (
                retry_cfg is not None
                and any(u in rej_live for u in watched)):
            now_v = (time.perf_counter() - t0) * time_scale
            while i < n and reqs[i].arrival_s <= now_v:
                _submit(reqs[i])
                i += 1
            if retry_cfg is not None and watched:
                _sweep_async_sheds()
            while retries and retries[0][0] <= time.perf_counter():
                _, ridx = heapq.heappop(retries)
                _submit(req_by_idx[ridx])
            # raw deque/slot reads, NOT _telemetry_status(): that sorts
            # the full latency windows per call, and this loop is inside
            # the very wall-clock the report measures
            timeline.append({
                "t_s": round(now_v / time_scale, 4),
                "queued": len(batcher._queue) + len(batcher._parked),
                "active": sum(s is not None for s in batcher._slots)})
            if batcher.pending:
                batcher.step(ticks=ticks)
            else:
                waits = []
                if i < n:
                    waits.append((reqs[i].arrival_s - now_v) / time_scale)
                if retries:
                    waits.append(retries[0][0] - time.perf_counter())
                if waits:
                    time.sleep(min(max(0.0, min(waits)), 0.05))
            if on_progress is not None and i - last_progress >= 64:
                last_progress = i
                on_progress(f"submitted {i}/{n}, pending {batcher.pending}")
    finally:
        remove()
        if slo is not None:
            batcher.set_slo(*prev_slo)
    wall = time.perf_counter() - t0

    gp1 = goodput_mod.summary()
    phases = {k: round(gp1.get(f"{k}_s", 0.0) - gp0.get(f"{k}_s", 0.0), 6)
              for k in ("compute", "data_wait", "checkpoint", "recompile")}
    phases["idle"] = round(max(0.0, gp1.get("idle_s", 0.0)
                               - gp0.get("idle_s", 0.0)), 6)

    waterfalls: List[dict] = []
    records: List[dict] = []
    completed = 0
    rejected = 0
    rej_map = getattr(batcher, "rejected", {})
    for r in reqs:
        uid = uid_by_idx.get(r.idx)
        w = collector.waterfall(uid, t0) if uid is not None else {"uid": None}
        w["idx"] = r.idx
        w["arrival_s"] = round(r.arrival_s, 6)
        w["shared_prefix"] = r.shared_prefix
        if attempts.get(r.idx, 1) > 1:
            w["attempts"] = attempts[r.idx]
        if uid is not None and uid in rej_map:
            # shed at admission (post-retry, when retries were
            # enabled): a first-class outcome — counts against SLO
            # attainment like offered-but-unfinished, never a no-show
            w["rejected"] = rej_map[uid]
            rejected += 1
            waterfalls.append(w)
            records.append({"n_out": 0, "ttft_ms": float("inf"),
                            "tpot_ms": None, "rejected": True})
            continue
        # coordinated-omission guard: the submit call can lag the
        # TRACE arrival (the loop was inside batcher.step when the
        # arrival time passed), and the batcher stamps TTFT at submit —
        # judging submit-relative TTFT would hide exactly the
        # regressions (longer tick windows) this harness exists to
        # catch.  Re-anchor TTFT on the scaled trace arrival.
        arr_rel = r.arrival_s / time_scale
        if w.get("t_submit_s") is not None:
            w["submit_lag_ms"] = round(
                1e3 * max(0.0, w["t_submit_s"] - arr_rel), 3)
        waterfalls.append(w)
        if w.get("t_retire_s") is not None:
            completed += 1
            ttft = w.get("ttft_ms", float("inf"))
            if w.get("t_first_token_s") is not None:
                w["ttft_submit_ms"] = ttft
                ttft = round(
                    1e3 * (w["t_first_token_s"] - arr_rel), 3)
                w["ttft_ms"] = ttft
            tpot = w.get("tpot_ms")
            # the displayed verdict must match the goodput judgment
            # (the batcher's retire tag is submit-relative)
            w["slo_ok"] = bool(
                w.get("n_out", 0) > 0 and ttft == ttft
                and ttft <= judge.ttft_ms
                and (tpot is None or tpot <= judge.tpot_ms))
            records.append({"n_out": w.get("n_out", 0),
                            "ttft_ms": ttft,
                            "tpot_ms": tpot})
        else:
            # offered but unfinished = a violation, not a no-show
            records.append({"n_out": 0, "ttft_ms": float("inf"),
                            "tpot_ms": None})
    g = compute_goodput(records, judge, wall)

    report = LoadReport(
        trace_sha256=trace.sha256(),
        trace_config=dataclasses.asdict(trace.config),
        slo=judge.to_jsonable(), wall_s=round(wall, 4), goodput=g,
        waterfalls=waterfalls, queue_timeline=timeline, phases=phases,
        completed=completed, offered=len(reqs), rejected=rejected)

    # registry + /statusz surfaces (scrapers see load runs without
    # reading the report file)
    telemetry_registry.counter(
        "loadgen_requests_replayed_total",
        "requests submitted by trace replays").inc(len(reqs))
    if g["slo_attainment"] is not None:
        telemetry_registry.gauge(
            "loadgen_slo_attainment_ratio",
            "last replay: fraction of requests meeting SLO"
        ).set(g["slo_attainment"])
    telemetry_registry.gauge(
        "loadgen_goodput_tokens_rate",
        "last replay: output tokens/s from requests meeting SLO"
    ).set(g["goodput_tok_s"])
    telemetry_registry.gauge(
        "loadgen_offered_tokens_rate",
        "last replay: output tokens/s across all completed requests"
    ).set(g["total_tok_s"])
    global _last_report
    _last_report = report
    from . import exporter as telemetry_exporter

    telemetry_exporter.register_status_provider("loadgen", _loadgen_status)
    return report


# ----------------------------------------------------------------------
# SLO calibration
# ----------------------------------------------------------------------
def calibrate_slo(batcher, *, prompt_len: int = 16, max_new: int = 8,
                  runs: int = 3, ttft_scale: float = 8.0,
                  tpot_scale: float = 6.0, seed: int = 0) -> SLOConfig:
    """Machine-relative SLO bounds: measure the box's own UNLOADED
    TTFT/TPOT with sequential single requests (call after warmup — a
    compile inside the calibration run would inflate the bounds), take
    the per-run minimum, and scale.  Absolute bounds don't transfer
    between a TPU and a CI runner; "k× the hardware's own floor" does —
    a scheduling regression shows up on either."""
    rng = np.random.default_rng(seed)
    collector = LifecycleCollector()
    remove = batcher.add_lifecycle_observer(collector)
    ttfts: List[float] = []
    tpots: List[float] = []
    try:
        for _ in range(max(1, runs)):
            prompt = rng.integers(0, batcher._vocab,
                                  size=(prompt_len,)).astype(np.int32)
            uid = batcher.submit(prompt, max_new_tokens=max_new)
            # wait() (not a hand-rolled spin): a shed calibration
            # request terminates the wait instead of deadlocking it
            batcher.wait([uid], ticks=4)
            ret = collector.first(uid, "retire")
            if ret is None:
                continue
            ttft = ret[1].get("ttft_ms")
            tpot = ret[1].get("tpot_ms")
            if ttft is not None and ttft == ttft:
                ttfts.append(float(ttft))
            if tpot is not None and tpot == tpot:
                tpots.append(float(tpot))
    finally:
        remove()
    if not ttfts or not tpots:
        raise RuntimeError("calibration produced no complete requests")
    return SLOConfig(ttft_ms=max(1.0, min(ttfts) * ttft_scale),
                     tpot_ms=max(0.1, min(tpots) * tpot_scale))


# ----------------------------------------------------------------------
# regression gate
# ----------------------------------------------------------------------
def check_baseline(report: dict, baseline: dict,
                   tolerance: Optional[float] = None
                   ) -> Tuple[bool, List[str]]:
    """Gate a replay ``report`` (``LoadReport.to_jsonable()``) against a
    checked-in ``baseline`` (``SERVE_LOAD_BASELINE.json``).

    Hard (exact) checks — failures here mean the *trace or decode
    determinism drifted*, which voids any perf comparison:
    - ``trace_sha256`` must match,
    - ``total_output_tokens`` must match (no EOS in random-token traces
      ⇒ every request runs to its max_new_tokens, so the count is
      machine-independent).

    Soft (tolerance) checks — the perf gate proper; bounds are
    machine-relative because the SLO is calibrated per box:
    - ``slo_attainment`` >= baseline ``slo_attainment_min`` − tolerance,
    - ``goodput_token_ratio`` >= ``goodput_token_ratio_min`` − tolerance.
    """
    tol = float(baseline.get("tolerance", 0.15)
                if tolerance is None else tolerance)
    msgs: List[str] = []
    ok = True
    want_sha = baseline.get("trace_sha256")
    if want_sha and report.get("trace_sha256") != want_sha:
        ok = False
        msgs.append(
            f"trace drift: sha256 {report.get('trace_sha256')} != "
            f"baseline {want_sha} (generator or config changed — "
            f"re-record the baseline deliberately)")
    g = report.get("goodput", {})
    want_tokens = baseline.get("total_output_tokens")
    if want_tokens is not None and \
            g.get("total_output_tokens") != want_tokens:
        ok = False
        msgs.append(
            f"determinism drift: total_output_tokens "
            f"{g.get('total_output_tokens')} != baseline {want_tokens} "
            f"(requests lost or generation lengths changed)")
    for key, base_key in (("slo_attainment", "slo_attainment_min"),
                          # dstpu-lint: disable-next-line=DSTPU006 -- report/baseline JSON keys, not registry metrics
                          ("goodput_token_ratio",
                           "goodput_token_ratio_min")):
        floor = baseline.get(base_key)
        got = g.get(key)
        if floor is None:
            continue
        if got is None or got < float(floor) - tol:
            ok = False
            msgs.append(
                f"goodput regression: {key}={got} < baseline "
                f"{base_key}={floor} - tolerance {tol}")
        else:
            msgs.append(f"{key}={got} vs floor {floor} (tol {tol}): ok")
    return ok, msgs
