"""Per-rank HTTP exporter: ``/metrics``, ``/healthz``, ``/statusz``.

PR 1's telemetry is pull-at-exit: the registry dumps when a rank dies
cleanly.  Production scraping wants a LIVE endpoint per rank.  This is
the smallest one that works — a stdlib ``http.server`` thread serving:

- ``/metrics`` — the registry's Prometheus text render, with scrape-time
  collectors (live-HBM sampling, the goodput ratio) refreshed first;
- ``/healthz`` — JSON liveness: heartbeat freshness and last-step age
  (503 when ``DSTPU_HEALTHZ_STALE_S`` is set and both are stale);
- ``/statusz`` — JSON operational state: the exporter's base fields
  (rank/pid/uptime/recompile counts/goodput breakdown) merged with
  named provider sections the engine, the serving batcher, the
  inference engine and the monitor register at init;
- ``/profilez`` — the per-executable roofline attribution table
  (``telemetry/attribution.py``; ``?capture_ms=N`` for an on-demand
  ``jax.profiler`` device trace);
- ``/alertz`` — active + recent structured alerts and detector
  thresholds (``telemetry/anomaly.py``);
- ``/tracez`` — retained request traces (``telemetry/reqtrace.py``):
  the index, ``?trace_id=`` for one trace's span tree, ``?full=1`` for
  every retained trace with spans (the fleet stitcher's fetch).

Opt-in: ``dstpu --telemetry_port P`` injects ``DSTPU_TELEMETRY_PORT``;
rank ``k`` serves on ``P + k`` (one process per host, so ports collide
only in local multi-process emulation — exactly where the offset
matters).  ``P = 0`` asks the OS for a free port per rank (the assigned
port is logged and published as the ``telemetry_exporter_port`` gauge).
No env/flag → no server thread at all.
"""
from __future__ import annotations

import json
import os
import threading
import time
import weakref
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

from ..testing import chaos as _chaos
from ..utils.logging import logger
from . import registry as _registry

__all__ = ["TelemetryExporter", "maybe_start", "get_exporter",
           "register_status_provider", "unregister_status_provider",
           "register_status_owner", "write_discovery",
           "TELEMETRY_PORT_ENV", "TELEMETRY_HOST_ENV",
           "HEALTHZ_STALE_ENV"]

TELEMETRY_PORT_ENV = "DSTPU_TELEMETRY_PORT"
TELEMETRY_HOST_ENV = "DSTPU_TELEMETRY_HOST"
HEALTHZ_STALE_ENV = "DSTPU_HEALTHZ_STALE_S"

_status_providers: Dict[str, Callable[[], Optional[dict]]] = {}


def register_status_provider(name: str,
                             fn: Callable[[], Optional[dict]]) -> None:
    """Register a ``/statusz`` section: ``fn()`` returns a JSON-able dict
    (or None to be omitted).  Last registration under a name wins — a
    rebuilt engine/batcher simply replaces its section."""
    _status_providers[name] = fn


def unregister_status_provider(name: str) -> None:
    _status_providers.pop(name, None)


def register_status_owner(name: str, owner, method: str) -> None:
    """Register ``owner.<method>()`` as a section WITHOUT pinning the
    owner alive: a strong ref from the process-lifetime provider table to
    an engine would pin its params (HBM!) after the caller dropped it."""
    ref = weakref.ref(owner)

    def provider():
        o = ref()
        if o is None:
            unregister_status_provider(name)
            return None
        return getattr(o, method)()

    register_status_provider(name, provider)


def _collect_status() -> dict:
    from . import goodput, recompile

    out: dict = {
        "rank": _registry._rank(),
        "pid": os.getpid(),
        "start_unixtime": _START_WALL,
        "uptime_s": round(time.monotonic() - _START_MONO, 3),
        "xla_recompiles_total": recompile.total_recompiles(),
        "goodput": goodput.summary(),
    }
    for name, fn in list(_status_providers.items()):
        try:
            section = fn()
        except Exception as e:       # one broken provider ≠ broken statusz
            section = {"error": repr(e)}
        if section is not None:
            out[name] = section
    return out


def _health() -> tuple:
    """(http_status, payload) for /healthz."""
    from ..utils import heartbeat
    from . import goodput

    hb_age = heartbeat.last_beat_age()
    step_age = goodput.last_step_age()
    payload = {
        "ok": True,
        "unix_time": time.time(),
        "rank": _registry._rank(),
        "pid": os.getpid(),
        "uptime_s": round(time.monotonic() - _START_MONO, 3),
        "heartbeat_age_s": None if hb_age is None else round(hb_age, 3),
        "last_step_age_s": None if step_age is None else round(step_age, 3),
    }
    try:
        stale_after = float(os.environ.get(HEALTHZ_STALE_ENV, "0") or 0)
    except ValueError:
        # a typo'd threshold must degrade to "no staleness gate", not
        # turn every probe into a 500 that restarts healthy workers
        stale_after = 0.0
    if stale_after > 0:
        ages = [a for a in (hb_age, step_age) if a is not None]
        # before any beat/step, age = uptime (a worker stuck in init is
        # just as dead as one stuck mid-loop)
        activity_age = min(ages) if ages else payload["uptime_s"]
        if activity_age > stale_after:
            payload["ok"] = False
            payload["stale_after_s"] = stale_after
            return 503, payload
    return 200, payload


class _Handler(BaseHTTPRequestHandler):
    registry: _registry.Registry = None  # type: ignore[assignment]
    # request tracer serving /tracez; None = resolve the reqtrace
    # module singleton at request time (a tracer installed AFTER the
    # exporter started must still be served)
    tracer = None

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 (http.server API)
        path = self.path.split("?", 1)[0]
        if _chaos.maybe_fire("exporter_blackhole") is not None:
            # injected wedged-observer fault: the scrape fails, serving
            # must not notice (a fleet aggregator sees the replica
            # degrade — HTTPError is a response, not a scrape failure)
            try:
                self._send(503, b"chaos: exporter blackhole\n",
                           "text/plain")
            except Exception:
                pass
            return
        try:
            if path == "/metrics":
                _registry.run_collectors()
                body = self.registry.render_prometheus().encode()
                self._send(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                code, payload = _health()
                self._send(code, json.dumps(payload).encode(),
                           "application/json")
            elif path == "/statusz":
                self._send(200, json.dumps(_collect_status()).encode(),
                           "application/json")
            elif path == "/profilez":
                # per-executable roofline attribution table
                # (telemetry/attribution.py); ?capture_ms=N additionally
                # records an on-demand jax.profiler device trace while
                # the workload keeps running
                from urllib.parse import parse_qs, urlparse

                from . import attribution

                payload = attribution.snapshot()
                q = parse_qs(urlparse(self.path).query)
                if "capture_ms" in q:
                    try:
                        ms = int(q["capture_ms"][0])
                    except ValueError:
                        ms = 0
                    if ms > 0:
                        payload["trace_dir"] = attribution.capture_trace(ms)
                self._send(200, json.dumps(payload).encode(),
                           "application/json")
            elif path == "/alertz":
                # evaluate (throttled) so a scrape never reads detectors
                # staler than ~1s, then serve active + recent alerts
                from . import anomaly

                anomaly.observe()
                self._send(200, json.dumps(anomaly.status()).encode(),
                           "application/json")
            elif path == "/tracez":
                # retained request traces (telemetry/reqtrace.py): the
                # tail-retention ring's index; ?trace_id= serves one
                # span tree, ?full=1 everything with spans (what the
                # fleet stitcher fetches)
                from urllib.parse import parse_qs, urlparse

                from . import reqtrace

                tracer = self.tracer or reqtrace.get_tracer()
                q = parse_qs(urlparse(self.path).query)
                if tracer is None:
                    self._send(200, json.dumps(
                        {"enabled": False, "retained": []}).encode(),
                        "application/json")
                elif "trace_id" in q:
                    tr = tracer.get_trace(q["trace_id"][0])
                    if tr is None:
                        self._send(404, json.dumps(
                            {"error": "trace not retained",
                             "trace_id": q["trace_id"][0]}).encode(),
                            "application/json")
                    else:
                        self._send(200, json.dumps(tr).encode(),
                                   "application/json")
                else:
                    payload = tracer.payload(full="full" in q)
                    self._send(200, json.dumps(payload).encode(),
                               "application/json")
            else:
                self._send(404, b"not found: try /metrics /healthz /statusz"
                                b" /profilez /alertz /tracez\n",
                           "text/plain")
        except BrokenPipeError:
            pass                     # scraper went away mid-response
        except Exception as e:       # a scrape must never kill the worker
            try:
                self._send(500, repr(e).encode(), "text/plain")
            except Exception:
                pass

    def log_message(self, fmt, *args):   # route access logs off stdout
        logger.debug("telemetry exporter: " + fmt % args)


class TelemetryExporter:
    """One daemon HTTP server thread over the (default) registry."""

    def __init__(self, port: int = 0, host: Optional[str] = None,
                 registry: Optional[_registry.Registry] = None,
                 tracer=None):
        self._requested_port = int(port)
        self.host = host if host is not None else \
            os.environ.get(TELEMETRY_HOST_ENV, "127.0.0.1")
        self.registry = registry or _registry.get_registry()
        # /tracez source; None = the reqtrace module singleton at
        # request time.  Explicit tracers exist for multi-exporter
        # emulation in one process (the fleet stitch tests).
        self.tracer = tracer
        self._server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> Optional[int]:
        return self._server.server_address[1] if self._server else None

    @property
    def url(self) -> Optional[str]:
        return f"http://{self.host}:{self.port}" if self._server else None

    def start(self) -> "TelemetryExporter":
        if self._server is not None:
            return self
        handler = type("_BoundHandler", (_Handler,),
                       {"registry": self.registry, "tracer": self.tracer})
        self._server = ThreadingHTTPServer(
            (self.host, self._requested_port), handler)
        self._server.daemon_threads = True
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="dstpu-telemetry",
            daemon=True)
        self._thread.start()
        self.registry.gauge(
            "telemetry_exporter_port",
            "bound port of this rank's telemetry HTTP server"
        ).set(float(self.port))
        logger.info(f"telemetry exporter serving /metrics /healthz "
                    f"/statusz /profilez /alertz /tracez on {self.url}")
        return self

    def stop(self) -> None:
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
            self._thread = None


def write_discovery(ex: "TelemetryExporter", rank: int,
                    directory: Optional[str] = None) -> Optional[str]:
    """Publish this rank's BOUND exporter address as
    ``<dir>/telemetry_rank<k>.json`` (host, port, pid).

    With ``--telemetry_port 0`` (OS-assigned) the actual port is
    unknowable to any scraper; this file is how the fleet plane learns
    it — the launcher aggregates every rank's file into the single
    ``fleet.json`` discovery file ``telemetry/fleet.py`` watches.
    ``directory`` defaults to ``DSTPU_METRICS_DIR`` (launcher-injected);
    no directory → no file.  Atomic rename so a mid-write scan never
    reads a torn JSON; best-effort (returns the path or None)."""
    directory = directory or os.environ.get(_registry.METRICS_DIR_ENV)
    if not directory or ex is None or ex.port is None:
        return None
    try:
        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"telemetry_rank{rank}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"rank": rank, "host": ex.host, "port": ex.port,
                       "pid": os.getpid(), "unix_time": time.time()}, fh)
        os.replace(tmp, path)
        return path
    except Exception as e:   # discovery is best-effort, never fatal
        logger.warning(f"telemetry: could not write discovery file: {e!r}")
        return None


_START_MONO = time.monotonic()
_START_WALL = time.time()
_exporter: Optional[TelemetryExporter] = None


def get_exporter() -> Optional[TelemetryExporter]:
    return _exporter


def disarm() -> None:
    """Stop and forget the module exporter (the launcher's guard against
    squatting a worker's port); ``maybe_start`` can arm a fresh one."""
    global _exporter
    if _exporter is not None:
        _exporter.stop()
        _exporter = None


def maybe_start(port: Optional[int] = None) -> Optional[TelemetryExporter]:
    """Start the per-rank exporter when configured; idempotent.

    ``port`` defaults to ``DSTPU_TELEMETRY_PORT`` (launcher-injected);
    unset/empty → no server.  A positive base port is rank-offset
    (rank k binds ``port + k``); 0 asks the OS for a free port."""
    global _exporter
    if _exporter is not None:
        if _exporter._server is not None:
            return _exporter
        _exporter = None        # a stopped exporter is not "armed"
    if port is None:
        env = os.environ.get(TELEMETRY_PORT_ENV)
        if env is None or env == "":
            return None
        try:
            port = int(env)
        except ValueError:
            logger.warning(f"ignoring non-integer {TELEMETRY_PORT_ENV}="
                           f"{env!r}")
            return None
    if port < 0:
        return None
    # rank from ENV ONLY: this runs at `import deepspeed_tpu`, and the
    # registry's jax.process_index() fallback would initialize the jax
    # backends before the user script can call jax.distributed.initialize()
    # (fatal on multi-host).  On real pods (one process per host, no
    # DSTPU_PROCESS_ID) every host correctly binds the base port.
    try:
        rank = int(os.environ.get("DSTPU_PROCESS_ID", "0"))
    except ValueError:
        rank = 0
    bound = port + rank if port > 0 else 0
    _chaos.maybe_install_env()   # exporter-only processes resolve the
    try:                         # DSTPU_CHAOS_PLAN here
        _exporter = TelemetryExporter(port=bound).start()
    except OSError as e:
        logger.warning(f"telemetry exporter failed to bind port {bound}: "
                       f"{e}; continuing without one")
        _exporter = None
    if _exporter is not None:
        # fleet discovery: publish the BOUND port (essential with
        # port 0) where the launcher's fleet.json aggregation reads it
        write_discovery(_exporter, rank)
    return _exporter
