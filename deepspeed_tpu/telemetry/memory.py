"""Per-executable + live-array HBM accounting.

OOM headroom on TPU is invisible until the allocator throws: the
compiled program's reservation is decided at compile time
(``compiled.memory_analysis()``) and the rest of HBM is whatever arrays
the host still holds alive.  This module turns both into scrapeable
gauges:

- :func:`memory_breakdown` — THE one normalizer over
  ``compiled.memory_analysis()`` (``profiling/flops_profiler.py`` and
  ``autotuning/autotuner.py`` previously each had a private copy).
  Bytes are PER-DEVICE: XLA analyzes the post-SPMD-partitioning
  program, so the numbers compare against one chip's HBM directly —
  no further division (see ``autotuner.py`` trial-fit logic).
- :func:`record_compiled` — publish a breakdown as
  ``hbm_exec_{args,output,temp,generated_code,total}_bytes{site=...}``
  gauges; wired at the AOT compile points (engine
  ``record_memory_profile``, serving ``warmup_windows`` /
  ``_warmup_admission``) where a Compiled object exists anyway.
- :func:`sample_live_hbm` — ``live_hbm_bytes`` (max per-device bytes
  pinned by live ``jax.Array``\\ s) + allocator stats where the backend
  exposes them; registered as a scrape-time collector so ``/metrics``
  always serves a fresh reading.
"""
from __future__ import annotations

from typing import Optional

from . import registry as _registry

__all__ = ["memory_breakdown", "peak_bytes", "record_compiled",
           "per_device_shard_bytes", "sample_live_hbm", "tree_bytes"]

# (gauge suffix, CompiledMemoryStats attribute)
_FIELDS = (
    ("args", "argument_size_in_bytes"),
    ("output", "output_size_in_bytes"),
    ("temp", "temp_size_in_bytes"),
    ("generated_code", "generated_code_size_in_bytes"),
)


def memory_breakdown(compiled) -> Optional[dict]:
    """Normalized per-device byte breakdown of a compiled executable.

    Returns ``{"args": .., "output": .., "temp": .., "generated_code":
    .., "total": ..}`` (floats, bytes) or None when the backend exposes
    no analysis.  ``total`` = args + output + temp — the data working
    set the program reserves in device memory, matching the fit checks
    the autotuner and flops profiler already apply (generated code
    lives in its own arena and is reported separately).
    """
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if isinstance(ma, (list, tuple)):            # some backends: [stats]
        ma = ma[0] if ma else None
    if ma is None:
        return None
    out = {key: float(getattr(ma, attr, 0) or 0) for key, attr in _FIELDS}
    out["total"] = out["args"] + out["output"] + out["temp"]
    return out


def peak_bytes(compiled) -> float:
    """Per-device working-set bytes of ``compiled`` (NaN when the
    backend exposes no analysis) — the autotuner's HBM-fit number."""
    bd = memory_breakdown(compiled)
    return bd["total"] if bd is not None else float("nan")


def record_compiled(compiled, site: str,
                    registry: Optional[_registry.Registry] = None
                    ) -> Optional[dict]:
    """Publish ``compiled``'s breakdown as per-site HBM gauges; returns
    the breakdown (None when unavailable — nothing is published).

    Every AOT compile point that records memory also feeds the roofline
    attribution plane (``telemetry/attribution.py``) its
    ``cost_analysis()`` FLOPs/bytes — one call site, two surfaces."""
    try:
        from . import attribution as _attribution

        _attribution.note_compiled(compiled, site)
    except Exception:
        pass        # attribution must never break a compile point
    bd = memory_breakdown(compiled)
    if bd is None:
        return None
    reg = registry or _registry.get_registry()
    for key, value in bd.items():
        reg.gauge(
            f"hbm_exec_{key}_bytes",
            f"per-device {key} bytes of the compiled executable",
            labelnames=("site",)).labels(site=site).set(value)
    return bd


def tree_bytes(tree) -> int:
    """Total GLOBAL bytes of every leaf in ``tree`` — works on real
    arrays and on ``ShapeDtypeStruct`` trees alike, so the same
    arithmetic sizes a page budget from an abstract cache tree
    (``inference/kvreuse.py``) and meters live parked prefill caches
    (``serving_parked_bytes``).  Logical bytes, not per-device shards —
    use :func:`per_device_shard_bytes` for residency."""
    import math

    import jax
    import numpy as np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        total += math.prod(leaf.shape) * np.dtype(leaf.dtype).itemsize
    return int(total)


def per_device_shard_bytes(arrays) -> tuple:
    """``({device: resident bytes}, n_arrays)`` over ``arrays``' local
    shards — THE accumulation shared by the live-array sampler and the
    inference params gauge.  Arrays that fail to expose shards (deleted
    or donated between listing and reading) are skipped, not fatal."""
    per_dev: dict = {}
    n = 0
    for arr in arrays:
        n += 1
        try:
            for shard in arr.addressable_shards:
                d = shard.device
                per_dev[d] = per_dev.get(d, 0) + (
                    shard.data.nbytes if shard.data is not None else 0)
        except Exception:
            continue
    return per_dev, n


def sample_live_hbm(registry: Optional[_registry.Registry] = None) -> dict:
    """Refresh the live-memory gauges; returns what was published.

    - ``live_hbm_bytes``: max over local devices of bytes pinned by live
      ``jax.Array`` shards (the committed side of OOM headroom);
    - ``live_hbm_arrays``: how many live arrays pin them;
    - ``hbm_device_in_use_bytes`` / ``hbm_device_limit_bytes``: the
      allocator's own view where the backend exposes ``memory_stats()``
      (TPU does; CPU usually returns nothing).

    Registered as a collector (:func:`registry.register_collector`), so
    every ``/metrics`` scrape and exit dump reads fresh values; also
    callable directly.  Cost is a walk of the live-array table — fine at
    scrape cadence, not for inner loops.
    """
    import sys

    jax = sys.modules.get("jax")    # never force jax in from a collector
    if jax is None:
        return {}
    reg = registry or _registry.get_registry()
    out: dict = {}
    try:
        per_dev, n = per_device_shard_bytes(jax.live_arrays())
        live = max(per_dev.values(), default=0)
        reg.gauge("live_hbm_bytes",
                  "max per-device bytes pinned by live jax arrays"
                  ).set(float(live))
        reg.gauge("live_hbm_arrays", "live jax arrays").set(float(n))
        out["live_hbm_bytes"] = float(live)
        out["live_hbm_arrays"] = float(n)
    except Exception:
        pass
    try:
        stats = jax.local_devices()[0].memory_stats() or {}

        # literal names at the call sites so DSTPU006 sees the
        # declarations; only declared when the backend reports the stat
        # (CPU's memory_stats() is empty)
        def gauge(name, src, desc):
            if src in stats:
                reg.gauge(name, desc).set(float(stats[src]))
                out[name] = float(stats[src])

        gauge("hbm_device_in_use_bytes", "bytes_in_use",
              "allocator bytes_in_use on device 0")
        gauge("hbm_device_peak_bytes", "peak_bytes_in_use",
              "allocator peak_bytes_in_use on device 0")
        gauge("hbm_device_limit_bytes", "bytes_limit",
              "allocator bytes_limit on device 0")
    except Exception:
        pass
    return out
