"""Process-wide metrics registry: counters, gauges, histograms.

The reference scatters observability across ``monitor/`` (event fan-out
to TensorBoard/W&B/CSV), ``utils/timer.py`` (log-line throughput) and the
FLOPs profiler — each with private state and no export surface.  This
module is the shared substrate: every subsystem publishes named metrics
into ONE registry, which renders to JSON (``snapshot()``) and to the
Prometheus text exposition format (``render_prometheus()``), so a
production deployment scrapes a single endpoint / reads a single per-rank
dump file instead of tailing logs.

Design notes
- Metric handles are get-or-create and idempotent: calling
  ``registry.counter("x")`` twice returns the same object; re-registering
  a name with a different type/labelset raises (a silent re-type would
  corrupt downstream dashboards).
- All mutation is lock-protected but O(dict lookup + float add): cheap
  enough for per-train-step / per-decode-tick increments.
- Histograms are fixed-bucket (Prometheus semantics: cumulative
  ``le``-bucket counts + ``_sum`` + ``_count``); no quantile sketching,
  so merging across ranks is exact addition.
- Per-rank export on exit: the launcher injects ``DSTPU_METRICS_DIR``;
  :func:`maybe_install_exit_dump` (called on ``telemetry`` import)
  registers an ``atexit`` writer of ``metrics_rank<k>.json`` there.
"""
from __future__ import annotations

import atexit
import json
import math
import os
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

__all__ = [
    "Counter", "Gauge", "Histogram", "Registry",
    "get_registry", "counter", "gauge", "histogram",
    "maybe_install_exit_dump", "flush_exit_dump", "register_collector",
    "run_collectors", "METRICS_DIR_ENV", "pct",
    "render_prometheus_snapshot",
    "SECONDS_BUCKETS", "MS_BUCKETS", "TPOT_MS_BUCKETS",
    "ACCEPT_LEN_BUCKETS", "BYTES_BUCKETS", "BUCKET_SCHEMAS",
]

METRICS_DIR_ENV = "DSTPU_METRICS_DIR"


def pct(sorted_xs, q: float) -> float:
    """THE repo-wide percentile convention — nearest-rank over an
    ascending sequence, NaN on empty.  ``ContinuousBatcher``
    (``latency_stats``/``/statusz``) and ``telemetry/loadgen.py`` both
    import this one function, so the serving surfaces and the load
    report cannot disagree on a tail."""
    if not sorted_xs:
        return float("nan")
    return sorted_xs[min(len(sorted_xs) - 1, int(q * len(sorted_xs)))]

# Prometheus default buckets skew web-request-sized; these cover both
# decode ticks (sub-ms) and train steps / checkpoint writes (minutes).
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

# -- named bucket schemas ----------------------------------------------
# Every ``histogram()`` call site references ONE of these by name instead
# of declaring ad-hoc tuples: the fleet aggregator
# (``telemetry/fleet.py``) merges histograms bucket-wise across replicas
# and can only assert "one schema per metric family" if the schemas are
# declared once.  ``serving_tpot_ms`` growing sub-ms buckets while other
# ms-histograms kept defaults is exactly the drift this centralization
# ends.
#
# seconds-denominated wall times (train steps, TTFT, checkpoint writes)
SECONDS_BUCKETS: Tuple[float, ...] = DEFAULT_BUCKETS
# ms-denominated wall times with a web-ish floor (scrape round-trips,
# queueing delays): 0.1 ms .. minutes
MS_BUCKETS: Tuple[float, ...] = (
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
    500.0, 1000.0, 2500.0, 5000.0, 10000.0, 30000.0, 60000.0)
# ms-denominated per-output-token latency: fused+paged decode on real
# chips lands in the tens of MICROseconds (below MS_BUCKETS' 0.1 floor,
# which collapsed the p50/p99 the anomaly detectors read), CPU-mesh
# tests in seconds
TPOT_MS_BUCKETS: Tuple[float, ...] = (
    0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0,
    25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0)
# accepted drafts per slot per verify tick land in [0, k]; covers any
# sane k without re-registering per config
ACCEPT_LEN_BUCKETS: Tuple[float, ...] = (
    0.0, 1.0, 2.0, 3.0, 4.0, 6.0, 8.0, 12.0, 16.0, 24.0, 32.0)
# byte-sized payloads (checkpoint writes, parked caches): KiB test
# fixtures through TiB-scale production checkpoints
BYTES_BUCKETS: Tuple[float, ...] = (
    1 << 10, 64 << 10, 1 << 20, 16 << 20, 128 << 20, 1 << 30,
    8 << 30, 64 << 30, 512 << 30, 1 << 42)

BUCKET_SCHEMAS: Dict[str, Tuple[float, ...]] = {
    "seconds": SECONDS_BUCKETS,
    "ms": MS_BUCKETS,
    "tpot_ms": TPOT_MS_BUCKETS,
    "accept_len": ACCEPT_LEN_BUCKETS,
    "bytes": BYTES_BUCKETS,
}


def _escape_label_value(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class _Child:
    """One labelset's value cell."""

    __slots__ = ("_lock", "_value")

    def __init__(self, lock: threading.Lock):
        self._lock = lock
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value


class _CounterChild(_Child):
    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount


class _GaugeChild(_Child):
    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value -= amount


class _HistogramChild:
    __slots__ = ("_lock", "buckets", "counts", "sum", "count")

    def __init__(self, lock: threading.Lock, buckets: Tuple[float, ...]):
        self._lock = lock
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)   # +1 for the +Inf bucket
        self.sum = 0.0
        self.count = 0

    def observe(self, value: float) -> None:
        value = float(value)
        if value != value:          # NaN observations poison sum/percentiles
            return
        with self._lock:
            # non-cumulative per-bucket counts internally; rendered
            # cumulatively (Prometheus ``le`` semantics) on export
            i = 0
            for i, b in enumerate(self.buckets):
                if value <= b:
                    break
            else:
                i = len(self.buckets)
            self.counts[i] += 1
            self.sum += value
            self.count += 1

    def cumulative(self) -> Iterable[Tuple[float, int]]:
        acc = 0
        for b, c in zip(self.buckets, self.counts):
            acc += c
            yield b, acc
        yield float("inf"), acc + self.counts[-1]


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Tuple[str, ...],
                 lock: threading.Lock, **kwargs):
        self.name = name
        self.help = help
        self.labelnames = labelnames
        self._lock = lock
        self._kwargs = kwargs
        self._children: Dict[Tuple[str, ...], object] = {}

    def _make_child(self):
        raise NotImplementedError

    def labels(self, *values, **kv):
        if kv:
            if values:
                raise ValueError("pass label values positionally OR by name")
            try:
                values = tuple(str(kv[n]) for n in self.labelnames)
            except KeyError as e:
                raise ValueError(
                    f"metric {self.name} labels are {self.labelnames}") from e
            if len(kv) != len(self.labelnames):
                raise ValueError(
                    f"metric {self.name} labels are {self.labelnames}, "
                    f"got {sorted(kv)}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"metric {self.name} takes {len(self.labelnames)} label "
                f"value(s) {self.labelnames}, got {len(values)}")
        with self._lock:
            child = self._children.get(values)
            if child is None:
                child = self._children[values] = self._make_child()
        return child

    def _default_child(self):
        if self.labelnames:
            raise ValueError(
                f"metric {self.name} has labels {self.labelnames}; call "
                f".labels(...) first")
        return self.labels()

    def samples(self):
        with self._lock:
            items = list(self._children.items())
        return items


class Counter(_Metric):
    kind = "counter"

    def _make_child(self):
        return _CounterChild(self._lock)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    @property
    def value(self) -> float:
        return self._default_child().value

    def total(self) -> float:
        """Sum over every labelset (convenience for tests/assertions)."""
        return sum(c.value for _, c in self.samples())


class Gauge(_Metric):
    kind = "gauge"

    def _make_child(self):
        return _GaugeChild(self._lock)

    def set(self, value: float) -> None:
        self._default_child().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default_child().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_child().dec(amount)

    @property
    def value(self) -> float:
        return self._default_child().value


class Histogram(_Metric):
    kind = "histogram"

    def _make_child(self):
        return _HistogramChild(self._lock, self._kwargs["buckets"])

    def observe(self, value: float) -> None:
        self._default_child().observe(value)


class Registry:
    """Named metric store with JSON + Prometheus export."""

    def __init__(self):
        # REENTRANT: the flight recorder's SIGTERM handler runs on the
        # main thread and reads the registry; a plain Lock held by the
        # interrupted increment would deadlock shutdown and lose the
        # forensics the handler exists to save
        self._lock = threading.RLock()
        self._metrics: Dict[str, _Metric] = {}

    # -- get-or-create handles ----------------------------------------
    def _get(self, cls, name: str, help: str,
             labelnames: Sequence[str], **kwargs) -> _Metric:
        labelnames = tuple(labelnames)
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, labelnames,
                                              self._lock, **kwargs)
                return m
        if not isinstance(m, cls):
            raise ValueError(
                f"metric {name!r} already registered as {m.kind}, "
                f"requested {cls.kind}")
        if m.labelnames != labelnames and labelnames:
            raise ValueError(
                f"metric {name!r} already registered with labels "
                f"{m.labelnames}, requested {labelnames}")
        if kwargs and m._kwargs != kwargs:
            # e.g. histogram buckets: observations landing in a different
            # bucket layout than the caller expects would silently corrupt
            # downstream dashboards — same failure class as a re-type
            raise ValueError(
                f"metric {name!r} already registered with "
                f"{m._kwargs}, requested {kwargs}")
        return m

    def counter(self, name: str, help: str = "",
                labelnames: Sequence[str] = ()) -> Counter:
        return self._get(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "",
              labelnames: Sequence[str] = ()) -> Gauge:
        return self._get(Gauge, name, help, labelnames)

    def histogram(self, name: str, help: str = "",
                  labelnames: Sequence[str] = (),
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, labelnames,
                         buckets=tuple(sorted(buckets)))

    # -- export --------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-able view of every metric: counters/gauges as plain
        values, histograms as cumulative ``le``-bucket maps + sum/count."""
        out: dict = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            entry: dict = {"type": m.kind, "help": m.help,
                           "labelnames": list(m.labelnames), "samples": []}
            for labelvalues, child in m.samples():
                labels = dict(zip(m.labelnames, labelvalues))
                if m.kind == "histogram":
                    entry["samples"].append({
                        "labels": labels,
                        "buckets": {_fmt_value(le): c
                                    for le, c in child.cumulative()},
                        "sum": child.sum, "count": child.count})
                else:
                    entry["samples"].append(
                        {"labels": labels, "value": child.value})
            out[m.name] = entry
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (v0.0.4)."""
        return render_prometheus_snapshot(self.snapshot())

    def dump(self, path: str) -> None:
        """Write ``snapshot()`` as JSON (atomic rename)."""
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.snapshot(), fh, indent=1)
        os.replace(tmp, path)

    def clear(self) -> None:
        """Drop every metric (test isolation helper)."""
        with self._lock:
            self._metrics.clear()


def render_prometheus_snapshot(snap: dict) -> str:
    """Prometheus text exposition for a ``snapshot()``-shaped dict.

    Module-level (not a ``Registry`` method) because the fleet
    aggregator (``telemetry/fleet.py``) renders structures it PARSED
    from remote replicas' ``/metrics`` with this same function —
    ``parse_prometheus(render_prometheus())`` round-trips
    byte-equivalently only because both directions share one renderer."""
    lines = []
    for name, entry in snap.items():
        if entry["help"]:
            lines.append(f"# HELP {name} {entry['help']}")
        lines.append(f"# TYPE {name} {entry['type']}")
        for s in entry["samples"]:
            base_labels = ",".join(
                f'{k}="{_escape_label_value(v)}"'
                for k, v in s["labels"].items())
            if entry["type"] == "histogram":
                for le, c in s["buckets"].items():
                    ls = (base_labels + "," if base_labels else "") \
                        + f'le="{le}"'
                    lines.append(f"{name}_bucket{{{ls}}} {c}")
                suffix = f"{{{base_labels}}}" if base_labels else ""
                lines.append(
                    f"{name}_sum{suffix} {_fmt_value(s['sum'])}")
                lines.append(f"{name}_count{suffix} {s['count']}")
            else:
                suffix = f"{{{base_labels}}}" if base_labels else ""
                lines.append(
                    f"{name}{suffix} {_fmt_value(s['value'])}")
    return "\n".join(lines) + ("\n" if lines else "")


_default_registry = Registry()


def get_registry() -> Registry:
    return _default_registry


def counter(name: str, help: str = "",
            labelnames: Sequence[str] = ()) -> Counter:
    return _default_registry.counter(name, help, labelnames)


def gauge(name: str, help: str = "",
          labelnames: Sequence[str] = ()) -> Gauge:
    return _default_registry.gauge(name, help, labelnames)


def histogram(name: str, help: str = "", labelnames: Sequence[str] = (),
              buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
    return _default_registry.histogram(name, help, labelnames, buckets)


def _rank() -> int:
    # launcher-injected rank first (set before jax initializes); fall back
    # to jax.process_index() only if jax is already imported (never force
    # the import from an atexit path)
    env = os.environ.get("DSTPU_PROCESS_ID")
    if env is not None:
        try:
            return int(env)
        except ValueError:
            pass
    import sys

    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return jax.process_index()
        except Exception:
            pass
    return 0


# -- scrape-time collectors --------------------------------------------
# Gauges that must be SAMPLED rather than pushed (live-array HBM, the
# goodput ratio) register a collector; every export surface (the HTTP
# exporter, the exit dump, the flight recorder) refreshes them via
# run_collectors() immediately before reading the registry, so a scrape
# never serves a value staler than the previous scrape.
_collectors: list = []


def register_collector(fn) -> None:
    """Register ``fn()`` to run before every export/scrape (idempotent)."""
    if fn not in _collectors:
        _collectors.append(fn)


def run_collectors() -> None:
    """Run every registered collector; one failing collector never takes
    down a scrape (or interpreter shutdown)."""
    for fn in list(_collectors):
        try:
            fn()
        except Exception:
            pass


_exit_dump_installed: Optional[str] = None


def disarm_exit_dump() -> None:
    """Make the (already-registered) exit dump a no-op — the launcher
    process must not clobber worker rank 0's ``metrics_rank0.json``
    when the operator exported ``DSTPU_METRICS_DIR`` shell-wide."""
    global _exit_dump_installed
    _exit_dump_installed = None


def flush_exit_dump() -> Optional[str]:
    """Write the per-rank exit dump NOW (refreshing collectors first).

    Callable from signal handlers as well as ``atexit`` — SIGTERM (the
    launcher killing a stale worker, or a preemption) does not run
    ``atexit`` hooks, so the flight recorder's SIGTERM handler calls this
    to keep the rank's final snapshot from being lost.  No-op when no
    dump directory was ever armed; returns the written path."""
    if not _exit_dump_installed:
        return None
    try:
        run_collectors()
        path = os.path.join(_exit_dump_installed,
                            f"metrics_rank{_rank()}.json")
        _default_registry.dump(path)
        return path
    except Exception:
        return None   # never let a metrics dump break shutdown paths


def maybe_install_exit_dump(directory: Optional[str] = None) -> Optional[str]:
    """Register an ``atexit`` dump of the default registry to
    ``<dir>/metrics_rank<k>.json``.  ``directory`` defaults to the
    ``DSTPU_METRICS_DIR`` env var (injected by the launcher); no-op when
    neither is set.  Returns the target directory (or None).

    The rank — and so the file name — resolves at DUMP time, not here:
    this usually runs at ``import deepspeed_tpu``, before jax has
    initialized, and a launcher-less multi-host job would otherwise bake
    rank 0 into every host and have them clobber one file."""
    global _exit_dump_installed
    directory = directory or os.environ.get(METRICS_DIR_ENV)
    if not directory:
        return None
    if _exit_dump_installed == directory:
        return directory
    already_armed = _exit_dump_installed is not None
    _exit_dump_installed = directory
    if not already_armed:
        atexit.register(flush_exit_dump)
    return directory
