"""Experiment monitoring fan-out.

Analog of reference ``deepspeed/monitor/monitor.py`` (``MonitorMaster``
:24 fanning out to TensorBoard/W&B/CSV writers).  Same event contract:
``write_events([(label, value, global_samples), ...])``, emitted only from
process 0 (the reference gates on ``dist.get_rank() == 0``).
"""
from __future__ import annotations

import csv
import os
import time
from typing import Any, Optional

from ..runtime.config import MonitorConfig
from ..utils.logging import logger


class _RegistryWriter:
    """Telemetry-registry sink: every ``write_events`` call ALSO lands in
    the process-wide metrics registry (``telemetry/registry.py``), so
    monitor events are scrapeable (Prometheus text / JSON snapshot)
    without configuring any external writer.  Event labels become label
    values of one ``monitor_event`` gauge family; the step rides along as
    ``monitor_event_samples`` so exporters can see staleness."""

    def __init__(self):
        from ..telemetry import registry as _reg

        self._events_total = _reg.counter(
            "monitor_events_total", "events fanned out via MonitorMaster")
        self._event = _reg.gauge(
            "monitor_event", "latest value per monitor event label",
            labelnames=("label",))
        self._event_step = _reg.gauge(
            "monitor_event_samples", "global_samples at the latest event",
            labelnames=("label",))
        self._last_event = _reg.gauge(
            "monitor_last_event_unixtime",
            "wall time of the latest write_events (exporter staleness)")

    def write_events(self, event_list):
        if not event_list:
            return   # an empty call must not refresh the staleness gauge
        for label, value, step in event_list:
            self._event.labels(label=str(label)).set(float(value))
            self._event_step.labels(label=str(label)).set(float(step))
        self._events_total.inc(len(event_list))
        self._last_event.set(time.time())

    def close(self):
        pass


class _CsvWriter:
    """Reference ``monitor/csv_monitor.py`` analog: one CSV per label."""

    def __init__(self, cfg: dict):
        self.output_path = cfg.get("output_path", "csv_monitor/")
        self.job_name = cfg.get("job_name", "DeepSpeedTPUJob")
        self._files: dict[str, Any] = {}

    def _file_for(self, label: str):
        if label not in self._files:
            d = os.path.join(self.output_path, self.job_name)
            os.makedirs(d, exist_ok=True)
            fh = open(os.path.join(d, label.replace("/", "_") + ".csv"), "a", newline="")
            self._files[label] = (fh, csv.writer(fh))
        return self._files[label]

    def write_events(self, event_list):
        for label, value, step in event_list:
            fh, writer = self._file_for(label)
            writer.writerow([int(step), float(value)])
            fh.flush()

    def close(self):
        for fh, _ in self._files.values():
            fh.close()
        self._files.clear()


class _TensorBoardWriter:
    """Reference ``monitor/tensorboard.py`` analog (SummaryWriter-backed)."""

    def __init__(self, cfg: dict):
        output_path = cfg.get("output_path", "")
        job_name = cfg.get("job_name", "DeepSpeedTPUJobName")
        log_dir = os.path.join(output_path, "tensorboard", job_name)
        try:
            from torch.utils.tensorboard import SummaryWriter
        except Exception:
            try:
                from tensorboardX import SummaryWriter  # type: ignore
            except Exception:
                logger.warning("tensorboard writer unavailable; disabling")
                self.summary_writer = None
                return
        os.makedirs(log_dir, exist_ok=True)
        self.summary_writer = SummaryWriter(log_dir=log_dir)

    def write_events(self, event_list):
        if self.summary_writer is None:
            return
        for label, value, step in event_list:
            self.summary_writer.add_scalar(label, float(value), int(step))
        self.summary_writer.flush()

    def close(self):
        if self.summary_writer is not None:
            self.summary_writer.close()


class _WandbWriter:
    """Reference ``monitor/wandb.py`` analog."""

    def __init__(self, cfg: dict):
        try:
            import wandb  # noqa: F401

            self.wandb = wandb
            self.wandb.init(project=cfg.get("project"), group=cfg.get("group"),
                            team=cfg.get("team"))
        except Exception:
            logger.warning("wandb unavailable; disabling")
            self.wandb = None

    def write_events(self, event_list):
        if self.wandb is None:
            return
        for label, value, step in event_list:
            self.wandb.log({label: float(value)}, step=int(step))

    def close(self):
        if self.wandb is not None:
            self.wandb.finish()


class MonitorMaster:
    def __init__(self, config: MonitorConfig):
        self.writers = []
        # the registry sink is unconditional (in-process, no I/O) but NOT
        # in ``writers``: ``enabled`` keeps meaning "an external writer is
        # configured" so callers' fetch-and-write gating is unchanged
        self._registry_sink = _RegistryWriter()
        self._rank0 = self._is_rank0()
        # /statusz section: which external writers are live on this rank
        from ..telemetry import exporter as telemetry_exporter

        telemetry_exporter.register_status_owner(
            "monitor", self, "_telemetry_status")
        if not self._rank0:
            return
        if config.tensorboard.get("enabled"):
            self.writers.append(_TensorBoardWriter(config.tensorboard))
        if config.wandb.get("enabled"):
            self.writers.append(_WandbWriter(config.wandb))
        if config.csv_monitor.get("enabled"):
            self.writers.append(_CsvWriter(config.csv_monitor))

    def _telemetry_status(self) -> dict:
        return {"rank0": self._rank0,
                "writers": [type(w).__name__.lstrip("_")
                            for w in self.writers]}

    @staticmethod
    def _is_rank0() -> bool:
        try:
            import jax

            return jax.process_index() == 0
        except Exception:
            return True

    @property
    def enabled(self) -> bool:
        return bool(self.writers)

    def write_events(self, event_list):
        self._registry_sink.write_events(event_list)
        for w in self.writers:
            w.write_events(event_list)

    def close(self):
        for w in self.writers:
            w.close()
