"""Seeded fault injection at named sites in the serving stack.

PR 8 built real abort/rollback machinery — paged-admission rollback,
backpressure at page exhaustion, drafter degradation — but every one of
those paths was only ever exercised by tests monkeypatching private
methods.  This module makes fault injection a first-class, deterministic
harness: the serving stack calls :func:`maybe_fire` at **named sites**,
and a :class:`ChaosPlan` (seeded) decides which invocation of which site
actually faults.  With no plan installed every site is a single
``is None`` check — the production path pays one attribute load.

Named sites (each is a real failure mode the stack must survive):

- ``prefill_failure`` — the admission prefill dispatch raises mid-batch
  (a transient device fault).  Exercises the contiguous path's
  pin/unpin ``finally`` and the paged path's ``abort_admit`` rollback;
  the batcher re-queues the group and retries next step.
- ``page_pool_exhaustion`` — ``try_admit`` reports an empty pool even
  though pages exist.  Exercises the backpressure path (tail re-queued
  IN ORDER, admission stops for the step).
- ``slow_tick`` — the decode window stalls ``arg`` seconds before
  dispatch (a straggler device / preempted core).  Drives real SLO
  burn, which is how the admission ladder is tested end-to-end.
- ``drafter_exception`` — the speculative drafter raises inside
  ``propose``.  The slot degrades to an empty proposal (plain-tick
  fallback) instead of killing the serve loop.
- ``exporter_blackhole`` — the telemetry exporter answers a scrape with
  503 (a wedged observer).  Serving must be unaffected; a fleet
  aggregator sees the replica degrade, not the process die.

Training sites (the other half of the fault surface — a multi-day run
on preemptible TPUs dies on exactly these):

- ``ckpt_save_failure`` — the checkpoint commit aborts mid-write (a
  storage fault / preemption landing inside the save), leaving a TORN
  directory: shards written, no ``MANIFEST``/``engine_state.json``/
  ``latest``.  The next save and the retention GC must tolerate the
  debris; ``verify_checkpoint`` must reject it.
- ``ckpt_corrupt_shard`` — one bit of a COMMITTED checkpoint file is
  flipped after publish (silent storage corruption).  ``verify``
  must catch it and ``load_checkpoint(fallback=True)`` must walk back
  to the previous verified checkpoint.
- ``sigterm_mid_step`` — SIGTERM delivered to the training process
  mid-step (the TPU/GKE preemption signal).  The
  ``AsyncCheckpointManager`` handler chain must flag/save and the run
  must resume from the preemption checkpoint.
- ``nonfinite_grad`` — NaN injected into one micro-batch's inputs so
  its gradients go non-finite (a poisoned sample / device flake).  The
  fp16 overflow-skip or the ``TrainGuard`` rollback must recover.
  (The site poisons the first floating-point batch leaf; an
  integer-only batch cannot produce the fault and the fire is logged
  as inert.)

Determinism: each site keeps its own invocation counter (counting from
plan install), and a :class:`FaultSpec` fires on exact invocation
indices (``at``), a period (``every``), or a seeded per-site coin
(``p``) — same plan + same workload ⇒ the same faults at the same
invocations.  Every fire is recorded (site, invocation index, wall
time) so a report can assert *exactly the planned faults fired*.

Install programmatically (:func:`install_plan`) or via
``DSTPU_CHAOS_PLAN=/path/to/plan.json`` (resolved by
``ContinuousBatcher``/exporter startup through
:func:`maybe_install_env`).  ``scripts/loadgen.py --chaos PLAN`` replays
a trace under a plan and reports goodput-under-faults next to the clean
number.
"""
from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import logger

__all__ = [
    "ChaosFault", "FaultSpec", "ChaosPlan", "ChaosEngine", "SITES",
    "CHAOS_PLAN_ENV", "install_plan", "clear", "get_engine", "maybe_fire",
    "maybe_install_env",
]

CHAOS_PLAN_ENV = "DSTPU_CHAOS_PLAN"

# the named sites threaded through the stack; a plan naming anything
# else is a typo, rejected at construction (a fault that can never fire
# would silently pass the "all planned faults fired" assertion's
# complement)
SITES: Tuple[str, ...] = (
    "prefill_failure",
    "page_pool_exhaustion",
    "slow_tick",
    "drafter_exception",
    "exporter_blackhole",
    # training sites (runtime/checkpointing.py, runtime/engine.py)
    "ckpt_save_failure",
    "ckpt_corrupt_shard",
    "sigterm_mid_step",
    "nonfinite_grad",
)


class ChaosFault(RuntimeError):
    """The injected failure.  Raised by sites whose real-world analog is
    an exception (prefill dispatch, drafter); other sites consume the
    spec behaviorally (exhaustion returns None, slow_tick sleeps)."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """When one site faults.  Exactly one trigger should be set:
    ``at`` (exact 0-based invocation indices of the site), ``every``
    (each Nth invocation), or ``p`` (seeded per-invocation coin).
    ``count`` caps total fires (0 = unlimited); ``arg`` is the
    site-specific payload (``slow_tick``: stall seconds)."""

    site: str
    at: Tuple[int, ...] = ()
    every: Optional[int] = None
    p: float = 0.0
    count: int = 0
    arg: Optional[float] = None

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown chaos site {self.site!r}; "
                             f"one of {SITES}")
        if not self.at and self.every is None and self.p <= 0.0:
            raise ValueError(
                f"fault at site {self.site!r} can never fire: set at=, "
                f"every=, or p=")
        if self.every is not None and self.every < 1:
            raise ValueError(f"every must be >= 1, got {self.every}")

    def to_jsonable(self) -> dict:
        out: dict = {"site": self.site}
        if self.at:
            out["at"] = list(self.at)
        if self.every is not None:
            out["every"] = self.every
        if self.p > 0.0:
            out["p"] = self.p
        if self.count:
            out["count"] = self.count
        if self.arg is not None:
            out["arg"] = self.arg
        return out


@dataclasses.dataclass(frozen=True)
class ChaosPlan:
    """A seeded set of fault specs — the whole fault-injection identity
    (same plan + same workload ⇒ same faults at the same invocations)."""

    seed: int = 0
    faults: Tuple[FaultSpec, ...] = ()

    @staticmethod
    def from_dict(d: dict) -> "ChaosPlan":
        faults = []
        for f in d.get("faults", ()):
            kw = dict(f)
            if "at" in kw:
                kw["at"] = tuple(int(x) for x in kw["at"])
            faults.append(FaultSpec(**kw))
        return ChaosPlan(seed=int(d.get("seed", 0)), faults=tuple(faults))

    @staticmethod
    def from_json(text: str) -> "ChaosPlan":
        return ChaosPlan.from_dict(json.loads(text))

    @staticmethod
    def load(path: str) -> "ChaosPlan":
        with open(path) as fh:
            return ChaosPlan.from_dict(json.load(fh))

    def to_jsonable(self) -> dict:
        return {"seed": self.seed,
                "faults": [f.to_jsonable() for f in self.faults]}

    def planned_sites(self) -> List[str]:
        return sorted({f.site for f in self.faults})


class ChaosEngine:
    """Evaluates a plan against per-site invocation counters.

    Thread-safe (the exporter site fires from the HTTP thread).  Every
    fire lands in the ``fired`` log and the
    ``chaos_faults_fired_total{site}`` counter, so "exactly the planned
    faults fired" is assertable from the log and scrapeable from
    ``/metrics``."""

    def __init__(self, plan: ChaosPlan):
        self.plan = plan
        self._lock = threading.Lock()
        self._invocations: Dict[str, int] = {s: 0 for s in SITES}
        self._fires_left: List[Optional[int]] = [
            (f.count if f.count else None) for f in plan.faults]
        # one rng lane per fault spec, seeded from (plan seed, spec
        # index): p-triggered fires are deterministic per spec no matter
        # how other sites interleave
        self._rngs = [np.random.default_rng([int(plan.seed), i])
                      for i in range(len(plan.faults))]
        self.fired: List[dict] = []
        from ..telemetry import registry as telemetry_registry

        self._m_fired = telemetry_registry.counter(
            "chaos_faults_fired_total",
            "injected faults fired, by site", labelnames=("site",))

    def fire(self, site: str) -> Optional[FaultSpec]:
        """One site invocation: returns the spec to apply when a fault
        fires here, else None.  At most one spec fires per invocation
        (first matching, plan order)."""
        with self._lock:
            inv = self._invocations[site]
            self._invocations[site] = inv + 1
            hit: Optional[FaultSpec] = None
            for idx, f in enumerate(self.plan.faults):
                if f.site != site:
                    continue
                left = self._fires_left[idx]
                if left is not None and left <= 0:
                    continue
                # ``every`` is "each Nth invocation" (1-based): the
                # first fire lands at invocation every-1, NOT at 0 — a
                # rare-fault plan (every: 100) must not be
                # indistinguishable from at: [0]
                match = (inv in f.at) or \
                    (f.every is not None
                     and inv % f.every == f.every - 1) or \
                    (f.p > 0.0 and self._rngs[idx].random() < f.p)
                if match:
                    if left is not None:
                        self._fires_left[idx] = left - 1
                    hit = f
                    break
            if hit is None:
                return None
            self.fired.append({"site": site, "invocation": inv,
                               "t": time.time()})
        self._m_fired.labels(site=site).inc()
        logger.warning(f"chaos: fired {site} at invocation {inv}")
        return hit

    def summary(self) -> dict:
        with self._lock:
            by_site: Dict[str, int] = {}
            for e in self.fired:
                by_site[e["site"]] = by_site.get(e["site"], 0) + 1
            return {
                "seed": self.plan.seed,
                "planned_sites": self.plan.planned_sites(),
                "invocations": {s: n for s, n in
                                self._invocations.items() if n},
                "fired": dict(by_site),
                "fired_events": list(self.fired),
            }

    def all_planned_fired(self) -> bool:
        """Every site named by the plan fired at least once."""
        fired_sites = {e["site"] for e in self.fired}
        return set(self.plan.planned_sites()) <= fired_sites


_engine: Optional[ChaosEngine] = None
_status_registered = False


def get_engine() -> Optional[ChaosEngine]:
    return _engine


def install_plan(plan: ChaosPlan) -> ChaosEngine:
    """Install (replacing any previous engine) and expose the
    ``/statusz`` ``chaos`` section."""
    global _engine, _status_registered
    _engine = ChaosEngine(plan)
    if not _status_registered:
        from ..telemetry import exporter as telemetry_exporter

        telemetry_exporter.register_status_provider(
            "chaos", lambda: None if _engine is None
            else _engine.summary())
        _status_registered = True
    logger.warning(
        f"chaos plan installed: seed={plan.seed} "
        f"sites={plan.planned_sites()} ({len(plan.faults)} fault specs)")
    return _engine


def clear() -> None:
    global _engine
    _engine = None


def maybe_fire(site: str) -> Optional[FaultSpec]:
    """THE site hook: one attribute load when no plan is installed."""
    eng = _engine
    if eng is None:
        return None
    return eng.fire(site)


def maybe_install_env() -> Optional[ChaosEngine]:
    """Install the plan named by ``DSTPU_CHAOS_PLAN`` (a JSON file
    path), once.  Called from batcher construction and exporter startup;
    unset env = no-op, the default path stays fault-free."""
    if _engine is not None:
        return _engine
    path = os.environ.get(CHAOS_PLAN_ENV, "").strip()
    if not path:
        return None
    try:
        return install_plan(ChaosPlan.load(path))
    except Exception as e:
        logger.warning(f"chaos: could not load plan {path!r}: {e!r}")
        return None


def assert_plan_fired(engine: Optional[ChaosEngine] = None,
                      expected: Optional[Sequence[Tuple[str, int]]] = None
                      ) -> dict:
    """CI helper: raise unless every planned site fired (and, with
    ``expected`` = [(site, invocation), ...], unless exactly those
    (site, invocation) pairs fired).  Returns the engine summary."""
    eng = engine or _engine
    if eng is None:
        raise AssertionError("no chaos engine installed")
    s = eng.summary()
    missing = set(eng.plan.planned_sites()) - set(s["fired"])
    if missing:
        raise AssertionError(
            f"planned chaos sites never fired: {sorted(missing)} "
            f"(fired: {s['fired']})")
    if expected is not None:
        got = [(e["site"], e["invocation"]) for e in s["fired_events"]]
        if sorted(got) != sorted((str(a), int(b)) for a, b in expected):
            raise AssertionError(
                f"fired faults {sorted(got)} != planned {sorted(expected)}")
    return s
