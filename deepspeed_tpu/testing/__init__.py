"""Test-support subsystems that ship with the package (not under
``tests/``): deterministic fault injection (:mod:`.chaos`) is wired
through the serving stack at named sites, so the abort/rollback paths
are exercisable from CI and load harnesses without monkeypatching."""
